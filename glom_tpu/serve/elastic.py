"""SLO-driven elastic serving: the control loop that closes PR 13's loop.

The capacity observatory can SEE trouble — every summary carries per-engine
`headroom` records and `telemetry watch --slo` stamps breaches — but until
now nothing could ACT: the fleet was pinned at the static `--engines N` the
operator guessed before traffic arrived. This module is the actuator:

  * `ElasticPolicy` is the pure decision core — a windowed low/high-water
    policy over the fleet's worst eligible headroom plus the live SLO
    breach signal, with MIN-DWELL hysteresis (a condition must hold
    continuously for `dwell_s` before it may act — a one-tick dip never
    spawns hardware), a post-action COOLDOWN (the fleet's response to the
    last action must land in the window before the next is considered),
    and hard `min_engines`/`max_engines` clamps. Fake-clock injectable,
    no threads, no engines — the tier-1 policy suite drives it directly.

  * `Autoscaler` is the supervised control thread: each tick it pulls the
    batcher's live capacity records (probation/draining engines are
    EXCLUDED from the headroom signal — a deliberately draining engine's
    0.0 would otherwise re-trigger the very loop that drained it),
    evaluates its in-process `SLOMonitor` (p99 / shed-rate rules over the
    batcher's own resolve/shed stream, fed by an event tap — breaches
    stamp live `slo_breach` records), asks the policy, and CHANGES THE
    FLEET:

      - scale-OUT builds a brand-new engine replica via the injected
        `engine_factory` (its own device group — serve/cli.py resolves
        one through parallel/runtime.make_engine_meshes), runs the FULL
        `warmup()` precompile OFF the hot path, and only then registers
        it with the batcher (worker, ladder, retry, affinity queue, page
        pool) — admission opens strictly after precompile completes
        (test-pinned). A factory/warmup failure (the `spawn_fault`
        injector rides here) ROLLS BACK loudly: a stamped
        `spawn_rollback` event, no registration, cooldown still charged
        so a persistent fault cannot hot-spin spawns.

      - scale-IN picks the LEAST-LOADED eligible engine (max headroom)
        and runs the batcher's graceful drain state machine
        (serve/batcher.drain_engine: stop admitting -> flush the
        in-flight dispatch and hand the affinity queue back -> migrate
        the engine's cache sessions' paged columns to a sibling pool,
        falling back to stamped `drain` invalidation when no sibling has
        page budget -> join the worker), then releases the engine's
        device state (`InferenceEngine.release`). `draining` is a
        first-class engine state distinct from `dead` — failover
        accounting, headroom aggregation, and the rejoin path never
        confuse a voluntary drain with a crash.

Every decision and transition is a stamped schema-v8 "serve" event
(`scale_out_decision` / `scale_out` / `admission_open` /
`scale_in_decision` / `drain_begin` / `drain_flush` / `drain_migrate` /
`drain_release` / `spawn_rollback`), each carrying the `decision_id` that
chains it to its decision and the triggering SIGNAL WINDOW embedded on
the decision record — the `ramp-serve` chaos scenario reconstructs the
full decision->spawn->admit and decision->drain->release chains from the
JSONL evidence alone (docs/RESILIENCE.md).

With `ServeConfig.elastic=False` (the default) none of this constructs:
the static `--engines N` path is byte-for-byte the PR 13 contract.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from glom_tpu.telemetry import schema


# The serve-event vocabulary of one elastic action, in chain order
# (docs/OBSERVABILITY.md "Elastic serving events"). perfetto renders
# these as global instants; the `n_engines` they carry samples the fleet
# counter track.
SCALE_EVENTS = (
    "scale_out_decision",
    "scale_out",
    "admission_open",
    "spawn_rollback",
    "scale_in_decision",
    "drain_begin",
    "drain_flush",
    "drain_migrate",
    "drain_release",
)


class ElasticPolicy:
    """The pure scale-out/scale-in decision core (no threads, no engines).

    Signals, in PRECEDENCE order:

      1. SLO breaches (`note_breach`, fed from the monitor's upper-bound
         rules — p99, shed_rate): a breach inside the window forces
         scale-out consideration even while headroom looks fine (latency
         is the contract; queue occupancy is only its proxy), and VETOES
         scale-in outright — capacity is never removed from a fleet that
         is currently failing its SLO.
      2. Headroom low/high water (`observe_headroom`, one worst-eligible
         sample per control tick): below `low_water` continuously for
         `dwell_s` arms scale-out; above `high_water` continuously for
         `dwell_s` (and no breach) arms scale-in.

    `decide(n_engines)` returns None or {"action", "signal"} with the
    triggering signal window embedded — the decision record stamps it
    verbatim. `acted()` starts the cooldown and resets both dwell
    anchors (the fleet's new shape must re-earn any further action)."""

    def __init__(
        self,
        *,
        min_engines: int = 1,
        max_engines: int = 4,
        low_water: float = 0.15,
        high_water: float = 0.6,
        dwell_s: float = 2.0,
        cooldown_s: float = 5.0,
        window_s: float = 10.0,
        clock=time.monotonic,
    ):
        if min_engines < 1:
            raise ValueError(f"min_engines {min_engines} must be >= 1")
        if max_engines < min_engines:
            raise ValueError(
                f"max_engines {max_engines} must be >= min_engines "
                f"{min_engines}"
            )
        if not 0.0 <= low_water < high_water <= 1.0:
            raise ValueError(
                f"need 0 <= low_water ({low_water}) < high_water "
                f"({high_water}) <= 1"
            )
        if dwell_s < 0 or cooldown_s < 0:
            raise ValueError(
                f"dwell_s {dwell_s} and cooldown_s {cooldown_s} must be >= 0"
            )
        if window_s <= 0:
            raise ValueError(f"window_s {window_s} must be > 0")
        self.min_engines = min_engines
        self.max_engines = max_engines
        self.low_water = low_water
        self.high_water = high_water
        self.dwell_s = dwell_s
        self.cooldown_s = cooldown_s
        self.window_s = window_s
        self._clock = clock
        self._samples: deque = deque()   # (t, worst eligible headroom)
        self._breaches: deque = deque()  # (t, rule)
        self._below_since: Optional[float] = None
        self._above_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._last_action: Optional[str] = None

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        for q in (self._samples, self._breaches):
            while q and q[0][0] < horizon:
                q.popleft()

    def observe_headroom(self, headroom: float) -> None:
        """Feed one control tick's WORST eligible headroom (the min
        across engines that are neither draining nor on probation —
        serve/batcher.capacity_records stamps the state). The dwell
        anchors track how long the value has been continuously past a
        water mark; crossing back resets them — the hysteresis that
        keeps a value oscillating AROUND a mark from ever acting."""
        now = self._clock()
        self._samples.append((now, float(headroom)))
        if headroom < self.low_water:
            if self._below_since is None:
                self._below_since = now
        else:
            self._below_since = None
        if headroom > self.high_water:
            if self._above_since is None:
                self._above_since = now
        else:
            self._above_since = None
        self._prune(now)

    def note_breach(self, rule: str) -> None:
        """One live SLO breach (the monitor's upper-bound rules). Ages
        out of the window like any sample."""
        self._breaches.append((self._clock(), str(rule)))
        self._prune(self._clock())

    def active_breaches(self) -> List[str]:
        self._prune(self._clock())
        return sorted({rule for _, rule in self._breaches})

    def _signal(self, now: float, rule: str) -> dict:
        """The triggering signal window the decision record embeds: the
        rule that fired, the last observed value, the water marks, and
        the trailing samples (time-relative, bounded) — enough to replay
        WHY from the JSONL alone."""
        tail = list(self._samples)[-32:]
        return {
            "rule": rule,
            "observed": round(tail[-1][1], 4) if tail else None,
            "low_water": self.low_water,
            "high_water": self.high_water,
            "dwell_s": self.dwell_s,
            "window_s": self.window_s,
            "breaches": self.active_breaches(),
            "samples": [
                [round(t - now, 3), round(h, 4)] for t, h in tail
            ],
        }

    def decide(self, n_engines: int) -> Optional[dict]:
        """The next fleet action at the current signals, or None. Clamped
        to [min_engines, max_engines]; silent inside the cooldown."""
        now = self._clock()
        self._prune(now)
        if (
            self._last_action_t is not None
            and now - self._last_action_t < self.cooldown_s
        ):
            return None
        breaches = self.active_breaches()
        below = (
            self._below_since is not None
            and now - self._below_since >= self.dwell_s
        )
        above = (
            self._above_since is not None
            and now - self._above_since >= self.dwell_s
        )
        if (breaches or below) and n_engines < self.max_engines:
            rule = breaches[0] if breaches else "headroom"
            return {"action": "scale_out", "signal": self._signal(now, rule)}
        if breaches:
            # Breach precedence: a breaching fleet never scales IN, no
            # matter how idle its queues look (shed_rate breaches are
            # exactly the idle-queues-because-we-reject shape).
            return None
        if above and n_engines > self.min_engines:
            return {"action": "scale_in", "signal": self._signal(now, "headroom")}
        return None

    def acted(self, action: str) -> None:
        now = self._clock()
        self._last_action_t = now
        self._last_action = action
        # The fleet changed shape: both dwell conditions must re-earn
        # their hold from scratch under the NEW capacity.
        self._below_since = None
        self._above_since = None

    @staticmethod
    def pick_drain_target(capacity_records: List[dict]) -> Optional[str]:
        """The least-loaded drainable engine: max headroom among records
        whose stamped state is "ok" (never a draining, probation, or
        dead engine). Ties break on name for determinism."""
        eligible = [
            c for c in capacity_records
            if c.get("state") == "ok"
            and isinstance(c.get("headroom"), (int, float))
        ]
        if not eligible:
            return None
        best = max(eligible, key=lambda c: (c["headroom"], c["engine"]))
        return best["engine"]


def resolve_policy(scfg, *, clock=time.monotonic) -> ElasticPolicy:
    """The one ServeConfig -> policy resolution (the ladder pattern)."""
    return ElasticPolicy(
        min_engines=scfg.min_engines,
        max_engines=scfg.max_engines,
        low_water=scfg.elastic_low_water,
        high_water=scfg.elastic_high_water,
        dwell_s=scfg.elastic_dwell_s,
        cooldown_s=scfg.elastic_cooldown_s,
        window_s=scfg.elastic_window_s,
        clock=clock,
    )


class Autoscaler:
    """The supervised control loop around one DynamicBatcher.

    `engine_factory()` must return a NOT-yet-registered engine replica
    (fresh name, own device group/mesh when configured) — the scaler
    runs its full `warmup()` precompile before the batcher ever sees it.
    `spawn_hook` is the chaos seam (resilience/faults.spawn_fault):
    called once per spawn attempt with {"attempt", "n_engines"}; a raise
    there — or anywhere in factory/warmup — is a failed scale-out and
    rolls back loudly. `rules` arms the in-process SLO monitor's
    upper-bound triggers (e.g. {"p99_ms": 250.0, "shed_rate": 0.05});
    the headroom low/high-water signal always rides the capacity
    records directly.

    Use as a context manager (or start()/stop()); `tick()` is public so
    the fake-clock tests drive one evaluation without any thread."""

    def __init__(
        self,
        batcher,
        engine_factory: Callable[[], object],
        *,
        policy: Optional[ElasticPolicy] = None,
        rules: Optional[Dict[str, float]] = None,
        writer=None,
        interval_s: float = 0.5,
        spawn_hook=None,
        warm_degraded_iters: Optional[int] = None,
        clock=time.monotonic,
    ):
        from glom_tpu.telemetry.aggregate import SLOMonitor

        if interval_s <= 0:
            raise ValueError(f"interval_s {interval_s} must be > 0")
        self.batcher = batcher
        self.engine_factory = engine_factory
        scfg = getattr(batcher.engine, "scfg", None)
        if policy is None:
            if scfg is None:
                policy = ElasticPolicy(clock=clock)
            else:
                policy = resolve_policy(scfg, clock=clock)
        self.policy = policy
        self.writer = writer
        self.interval_s = interval_s
        self.spawn_hook = spawn_hook
        self.warm_degraded_iters = warm_degraded_iters
        self._clock = clock
        self.monitor = SLOMonitor(
            dict(rules or {}),
            window_s=policy.window_s,
            writer=writer,
            clock=clock,
        )
        # The batcher's event tap feeds the monitor every emitted serve
        # record (resolve leaves, sheds) — the autoscaler sees the same
        # stream `telemetry watch` would tail, in process, with no file.
        batcher.add_event_tap(self.monitor.observe)
        batcher.attach_elastic(self)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Counters + the fleet timeline, guarded by one lock: the control
        # thread writes, record()/summary readers snapshot.
        self._lock = threading.Lock()
        self._t0 = clock()
        self._decision_seq = 0
        self._spawn_attempts = 0
        self.n_scale_outs = 0
        self.n_scale_ins = 0
        self.n_spawn_failures = 0
        self.n_ticks = 0
        self.n_migrated_sessions = 0
        self.n_invalidated_sessions = 0
        self.migrated_bytes = 0
        self._spawn_ms: List[float] = []
        self._timeline: List[list] = [
            [0.0, batcher.n_active_engines()]
        ]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="glom-serve-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=60.0)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        # Supervised: one tick's exception is stamped evidence, never the
        # loop's death — a control plane that silently stops controlling
        # is the failure mode this file exists to not have.
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except BaseException as e:  # noqa: BLE001 — stamped, loop lives
                self._emit(
                    {
                        "error": "autoscaler-tick",
                        "value": None,
                        "note": f"{type(e).__name__}: {e}"[:300],
                    },
                    kind="error",
                )

    # -- the control tick --------------------------------------------------

    def tick(self) -> Optional[dict]:
        """One evaluation: capacity -> signals -> policy -> (maybe) act.
        Returns the decision taken, or None."""
        caps = self.batcher.capacity_records()
        for c in caps:
            # Live capacity on the stream each tick (the summary-only
            # cadence is too coarse for a watch tailing the scale loop)
            # and into the monitor (which skips probation/draining
            # headroom — the capacity-record contract).
            self._emit(c, kind=None)
            self.monitor.observe(c)
        eligible = [
            c["headroom"] for c in caps
            if c.get("state") == "ok"
            and isinstance(c.get("headroom"), (int, float))
        ]
        if eligible:
            self.policy.observe_headroom(min(eligible))
        for b in self.monitor.evaluate():
            # Lower-bound rules (headroom) are the policy's OWN water
            # marks — only upper-bound breaches (p99, shed_rate) feed
            # the breach-precedence signal.
            if b.get("bound") != "lower":
                self.policy.note_breach(b["rule"])
        with self._lock:
            self.n_ticks += 1
        n = self.batcher.n_active_engines()
        decision = self.policy.decide(n)
        if decision is None:
            return None
        if decision["action"] == "scale_out":
            self._scale_out(n, decision["signal"])
        else:
            self._scale_in(n, decision["signal"], caps)
        return decision

    def _next_decision(self) -> int:
        with self._lock:
            self._decision_seq += 1
            return self._decision_seq

    def _note_fleet(self, n: int) -> None:
        with self._lock:
            self._timeline.append(
                [round(self._clock() - self._t0, 3), n]
            )

    def _scale_out(self, n: int, signal: dict) -> None:
        decision_id = self._next_decision()
        self._emit(
            {
                "event": "scale_out_decision",
                "decision_id": decision_id,
                "n_engines": n,
                "signal": signal,
            }
        )
        with self._lock:
            self._spawn_attempts += 1
            attempt = self._spawn_attempts
        t0 = self._clock()
        try:
            if self.spawn_hook is not None:
                self.spawn_hook({"attempt": attempt, "n_engines": n})
            engine = self.engine_factory()
            # The FULL precompile, off the hot path: every bucket
            # signature (and the ladder's degraded route when armed)
            # compiles before admission can open. A fake engine without
            # warmup() is the policy tests' no-op.
            warmup = getattr(engine, "warmup", None)
            if callable(warmup):
                warmup()
                if self.warm_degraded_iters is not None:
                    warmup(iters_override=self.warm_degraded_iters)
        except BaseException as e:  # noqa: BLE001 — rollback is the contract
            # FAILED scale-out: no registration, loud evidence, cooldown
            # still charged (a persistently failing spawn must not retry
            # every tick at full speed).
            with self._lock:
                self.n_spawn_failures += 1
            self.policy.acted("spawn_rollback")
            self._emit(
                {
                    "event": "spawn_rollback",
                    "decision_id": decision_id,
                    "n_engines": n,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                }
            )
            return
        spawn_ms = round(1e3 * (self._clock() - t0), 3)
        name = self.batcher.add_engine(engine)
        with self._lock:
            self.n_scale_outs += 1
            self._spawn_ms.append(spawn_ms)
        self.policy.acted("scale_out")
        self._note_fleet(n + 1)
        self._emit(
            {
                "event": "scale_out",
                "decision_id": decision_id,
                "engine": name,
                "spawn_ms": spawn_ms,
                "n_engines": n + 1,
                "signal": signal,
            }
        )
        # Admission is OPEN from add_engine's worker start — stamped as
        # its own transition so the chaos chain check can pin the order:
        # decision -> (warmup inside spawn_ms) -> admission.
        self._emit(
            {
                "event": "admission_open",
                "decision_id": decision_id,
                "engine": name,
                "n_engines": n + 1,
            }
        )

    def _scale_in(self, n: int, signal: dict, caps: List[dict]) -> None:
        target = self.policy.pick_drain_target(caps)
        if target is None:
            return
        decision_id = self._next_decision()
        self._emit(
            {
                "event": "scale_in_decision",
                "decision_id": decision_id,
                "engine": target,
                "n_engines": n,
                "signal": signal,
            }
        )
        try:
            stats = self.batcher.drain_engine(
                target, detail={"decision_id": decision_id}
            )
        except ValueError as e:
            # Raced a death/concurrent drain: the fleet can no longer
            # spare the target — stamped, no action, cooldown charged.
            self.policy.acted("drain_abort")
            self._emit(
                {
                    "event": "drain_abort",
                    "decision_id": decision_id,
                    "engine": target,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                }
            )
            return
        engine = self.batcher.engine_by_name(target)
        release = getattr(engine, "release", None)
        if callable(release):
            release()
        with self._lock:
            self.n_scale_ins += 1
            self.n_migrated_sessions += stats.get("n_migrated", 0)
            self.n_invalidated_sessions += stats.get("n_invalidated", 0)
            self.migrated_bytes += stats.get("bytes_migrated", 0)
        self.policy.acted("scale_in")
        self._note_fleet(n - 1)
        self._emit(
            {
                "event": "drain_release",
                "decision_id": decision_id,
                "engine": target,
                "n_engines": n - 1,
                **{
                    k: stats.get(k)
                    for k in (
                        "n_migrated", "n_invalidated", "bytes_migrated",
                        "flush_ok",
                    )
                },
            }
        )

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict, kind: Optional[str] = "serve") -> None:
        from glom_tpu.tracing.flight import write_or_observe

        if kind is None:
            # Already-stamped records (the capacity rollup) pass through.
            write_or_observe(self.writer, rec)
            return
        if kind == "serve":
            from glom_tpu.serve.events import emit_serve

            stamped = emit_serve(self.writer, rec)
            # Scale events join the batcher's tap fan-out: the forecast
            # emitter's spawn-lead-time model (telemetry/forecast.py)
            # reads spawn_ms from the same in-process stream `telemetry
            # watch` would tail — the scale_out record must not exist
            # only on disk. Taps never kill the control loop.
            for tap in list(getattr(self.batcher, "_taps", ())):
                try:
                    tap(stamped)
                except Exception:  # noqa: BLE001
                    pass
            return
        write_or_observe(self.writer, schema.stamp(rec, kind=kind))

    def record(self) -> dict:
        """The `elastic` summary nest (serve/batcher.summary_record nests
        it; `telemetry compare` flattens it as serve_elastic.* rows with
        spawn latency and migration bytes classified as costs)."""
        with self._lock:
            spawn_ms = list(self._spawn_ms)
            rec = {
                "n_scale_outs": self.n_scale_outs,
                "n_scale_ins": self.n_scale_ins,
                "n_spawn_failures": self.n_spawn_failures,
                "n_ticks": self.n_ticks,
                "n_migrated_sessions": self.n_migrated_sessions,
                "n_invalidated_sessions": self.n_invalidated_sessions,
                "migrated_bytes": self.migrated_bytes,
                "spawn_ms_mean": (
                    round(sum(spawn_ms) / len(spawn_ms), 3)
                    if spawn_ms else None
                ),
                "spawn_ms_max": max(spawn_ms) if spawn_ms else None,
                # The RAW spawn latencies, in spawn order: the lead-time
                # model (telemetry/forecast.py SpawnLeadTimeModel) fits
                # its percentile from these, not from the mean/max pair.
                "spawn_ms": spawn_ms,
                "n_engines": self.batcher.n_active_engines(),
                "n_engines_peak": max(n for _, n in self._timeline),
                # The fleet-size timeline ([t_rel_s, n_engines] per
                # change): the bench's n_engines row and perfetto's
                # counter track both read it.
                "timeline": [list(e) for e in self._timeline],
            }
        return rec
