"""SLO-driven elastic serving: the control loop that closes PR 13's loop.

The capacity observatory can SEE trouble — every summary carries per-engine
`headroom` records and `telemetry watch --slo` stamps breaches — but until
now nothing could ACT: the fleet was pinned at the static `--engines N` the
operator guessed before traffic arrived. This module is the actuator:

  * `ElasticPolicy` is the pure decision core — a windowed low/high-water
    policy over the fleet's worst eligible headroom plus the live SLO
    breach signal, with MIN-DWELL hysteresis (a condition must hold
    continuously for `dwell_s` before it may act — a one-tick dip never
    spawns hardware), a post-action COOLDOWN (the fleet's response to the
    last action must land in the window before the next is considered),
    and hard `min_engines`/`max_engines` clamps. Fake-clock injectable,
    no threads, no engines — the tier-1 policy suite drives it directly.

  * `Autoscaler` is the supervised control thread: each tick it pulls the
    batcher's live capacity records (probation/draining engines are
    EXCLUDED from the headroom signal — a deliberately draining engine's
    0.0 would otherwise re-trigger the very loop that drained it),
    evaluates its in-process `SLOMonitor` (p99 / shed-rate rules over the
    batcher's own resolve/shed stream, fed by an event tap — breaches
    stamp live `slo_breach` records), asks the policy, and CHANGES THE
    FLEET:

      - scale-OUT builds a brand-new engine replica via the injected
        `engine_factory` (its own device group — serve/cli.py resolves
        one through parallel/runtime.make_engine_meshes), runs the FULL
        `warmup()` precompile OFF the hot path, and only then registers
        it with the batcher (worker, ladder, retry, affinity queue, page
        pool) — admission opens strictly after precompile completes
        (test-pinned). A factory/warmup failure (the `spawn_fault`
        injector rides here) ROLLS BACK loudly: a stamped
        `spawn_rollback` event, no registration, cooldown still charged
        so a persistent fault cannot hot-spin spawns.

      - scale-IN picks the LEAST-LOADED eligible engine (max headroom)
        and runs the batcher's graceful drain state machine
        (serve/batcher.drain_engine: stop admitting -> flush the
        in-flight dispatch and hand the affinity queue back -> migrate
        the engine's cache sessions' paged columns to a sibling pool,
        falling back to stamped `drain` invalidation when no sibling has
        page budget -> join the worker), then releases the engine's
        device state (`InferenceEngine.release`). `draining` is a
        first-class engine state distinct from `dead` — failover
        accounting, headroom aggregation, and the rejoin path never
        confuse a voluntary drain with a crash.

Every decision and transition is a stamped schema-v8 "serve" event
(`scale_out_decision` / `scale_out` / `admission_open` /
`scale_in_decision` / `drain_begin` / `drain_flush` / `drain_migrate` /
`drain_release` / `spawn_rollback`), each carrying the `decision_id` that
chains it to its decision and the triggering SIGNAL WINDOW embedded on
the decision record — the `ramp-serve` chaos scenario reconstructs the
full decision->spawn->admit and decision->drain->release chains from the
JSONL evidence alone (docs/RESILIENCE.md).

With `ServeConfig.elastic=False` (the default) none of this constructs:
the static `--engines N` path is byte-for-byte the PR 13 contract.

Schema v10 makes the loop ANTICIPATORY and AUDITABLE (ROADMAP item 4's
action half, docs/SERVING.md "Anticipatory autoscaling"):

  * With `elastic_anticipatory=True` the policy reads the live load
    forecast (telemetry/forecast.py ForecastEmitter) and the spawn-lead-
    time quantile each tick, and a positive PREDICTED DEFICIT — forecast
    load at `now + lead_time_ms` minus the fleet's usable capacity
    (measured service rate x `elastic_target_utilization`) — arms
    scale-out and vetoes scale-in. The signal only fires once both
    models have MATURED (a scored `forecast_abs_err`, real spawn
    evidence); until then the semantics are the reactive path
    bit-for-bit.

  * Every decision that acts stamps a "decision" record: the full
    evidence bundle (headroom/dwell/breach state, the forecast believed
    at decision time, lead quantile, measured service rate), the action,
    and the per-fleet `decision_id` chain it extends. decide() computes
    the action FROM that bundle via the pure `telemetry/audit.py
    policy_action`, so `python -m glom_tpu.telemetry audit` can replay
    the JSONL and demand the stamped action back bit-for-bit.

  * `warm_pool=N` holds N pre-spawned, fully-warmed SPARES outside
    admission (never registered with the batcher — a spare is not a
    husk and serves no traffic): scale-out PROMOTES a spare at ~0 spawn
    cost (stamped "spare_promote" with the owning decision_id),
    scale-in DEMOTES the drained engine back into the pool instead of
    releasing it ("spare_demote"), and the pre-spawn latencies
    ("spare_spawn") bootstrap the lead-time model before the first
    live scale-out.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from glom_tpu.telemetry import schema


# The serve-event vocabulary of one elastic action, in chain order
# (docs/OBSERVABILITY.md "Elastic serving events"). perfetto renders
# these as global instants; the `n_engines` they carry samples the fleet
# counter track.
SCALE_EVENTS = (
    "scale_out_decision",
    "scale_out",
    "admission_open",
    "spawn_rollback",
    "scale_in_decision",
    "drain_begin",
    "drain_flush",
    "drain_migrate",
    "drain_release",
    "spare_spawn",
    "spare_promote",
    "spare_demote",
)


class ElasticPolicy:
    """The pure scale-out/scale-in decision core (no threads, no engines).

    Signals, in PRECEDENCE order:

      1. SLO breaches (`note_breach`, fed from the monitor's upper-bound
         rules — p99, shed_rate): a breach inside the window forces
         scale-out consideration even while headroom looks fine (latency
         is the contract; queue occupancy is only its proxy), and VETOES
         scale-in outright — capacity is never removed from a fleet that
         is currently failing its SLO.
      2. Headroom low/high water (`observe_headroom`, one worst-eligible
         sample per control tick): below `low_water` continuously for
         `dwell_s` arms scale-out; above `high_water` continuously for
         `dwell_s` (and no breach) arms scale-in.

    `decide(n_engines)` returns None or {"action", "signal"} with the
    triggering signal window embedded — the decision record stamps it
    verbatim. `acted()` starts the cooldown and resets both dwell
    anchors (the fleet's new shape must re-earn any further action)."""

    def __init__(
        self,
        *,
        min_engines: int = 1,
        max_engines: int = 4,
        low_water: float = 0.15,
        high_water: float = 0.6,
        dwell_s: float = 2.0,
        cooldown_s: float = 5.0,
        window_s: float = 10.0,
        anticipatory: bool = False,
        target_utilization: float = 0.8,
        low_classes=frozenset(),
        class_weights: Optional[Dict[str, float]] = None,
        clock=time.monotonic,
    ):
        if min_engines < 1:
            raise ValueError(f"min_engines {min_engines} must be >= 1")
        if max_engines < min_engines:
            raise ValueError(
                f"max_engines {max_engines} must be >= min_engines "
                f"{min_engines}"
            )
        if not 0.0 <= low_water < high_water <= 1.0:
            raise ValueError(
                f"need 0 <= low_water ({low_water}) < high_water "
                f"({high_water}) <= 1"
            )
        if dwell_s < 0 or cooldown_s < 0:
            raise ValueError(
                f"dwell_s {dwell_s} and cooldown_s {cooldown_s} must be >= 0"
            )
        if window_s <= 0:
            raise ValueError(f"window_s {window_s} must be > 0")
        if not 0.0 < target_utilization <= 1.0:
            raise ValueError(
                f"target_utilization {target_utilization} must be in (0, 1]"
            )
        self.min_engines = min_engines
        self.max_engines = max_engines
        self.low_water = low_water
        self.high_water = high_water
        self.dwell_s = dwell_s
        self.cooldown_s = cooldown_s
        self.window_s = window_s
        self.anticipatory = bool(anticipatory)
        self.target_utilization = float(target_utilization)
        # QoS (glom_tpu/serve/qos.py): breaches of rules scoped to a
        # LOW class (e.g. "p99_ms[batch]") are recorded but NON-BINDING
        # — they neither force scale-out nor veto an earned scale-in.
        # Cheap-tenant pressure alone never spends hardware; the weights
        # ride the evidence bundle so the audit can score class-weighted
        # regret. Empty/None = classless semantics bit-for-bit.
        self.low_classes = frozenset(str(c) for c in (low_classes or ()))
        self.class_weights = (
            {str(k): float(v) for k, v in class_weights.items()}
            if class_weights else None
        )
        self._clock = clock
        self._samples: deque = deque()   # (t, worst eligible headroom)
        self._breaches: deque = deque()  # (t, rule)
        self._below_since: Optional[float] = None
        self._above_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._last_action: Optional[str] = None
        # Anticipatory inputs, refreshed by the autoscaler each tick
        # (telemetry/forecast.py): the latest closed-window load
        # forecast, the spawn-lead-time quantile, the fleet's measured
        # ok-engine service rate. All default None = reactive semantics.
        self._forecast: Optional[dict] = None
        self._lead_time_ms: Optional[float] = None
        self._lead_quantile: Optional[float] = None
        self._service_rate_rps: Optional[float] = None

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        for q in (self._samples, self._breaches):
            while q and q[0][0] < horizon:
                q.popleft()

    def observe_headroom(self, headroom: float) -> None:
        """Feed one control tick's WORST eligible headroom (the min
        across engines that are neither draining nor on probation —
        serve/batcher.capacity_records stamps the state). The dwell
        anchors track how long the value has been continuously past a
        water mark; crossing back resets them — the hysteresis that
        keeps a value oscillating AROUND a mark from ever acting."""
        now = self._clock()
        self._samples.append((now, float(headroom)))
        if headroom < self.low_water:
            if self._below_since is None:
                self._below_since = now
        else:
            self._below_since = None
        if headroom > self.high_water:
            if self._above_since is None:
                self._above_since = now
        else:
            self._above_since = None
        self._prune(now)

    def note_breach(self, rule: str) -> None:
        """One live SLO breach (the monitor's upper-bound rules). Ages
        out of the window like any sample."""
        self._breaches.append((self._clock(), str(rule)))
        self._prune(self._clock())

    def note_forecast(self, rec: Optional[dict]) -> None:
        """The latest closed-window load forecast record (the fields the
        evidence bundle stamps: predicted / forecast_abs_err / horizon_s
        / trend_per_s / t). None clears it."""
        self._forecast = dict(rec) if rec else None

    def note_lead_time(
        self, lead_ms: Optional[float], quantile: Optional[float] = None
    ) -> None:
        """The spawn-lead-time model's current quantile estimate (None =
        no spawn evidence yet — the anticipatory signal stays dark)."""
        self._lead_time_ms = float(lead_ms) if lead_ms is not None else None
        self._lead_quantile = (
            float(quantile) if quantile is not None else None
        )

    def note_service_rate(self, rate_rps: Optional[float]) -> None:
        """The fleet's measured service rate (sum of ok engines'
        service_rate_rps from the capacity records) — the capacity side
        of the anticipated deficit."""
        self._service_rate_rps = (
            float(rate_rps) if rate_rps is not None else None
        )

    def active_breaches(self) -> List[str]:
        self._prune(self._clock())
        return sorted({rule for _, rule in self._breaches})

    def _signal(self, now: float, rule: str) -> dict:
        """The triggering signal window the decision record embeds: the
        rule that fired, the last observed value, the water marks, and
        the trailing samples (time-relative, bounded) — enough to replay
        WHY from the JSONL alone."""
        tail = list(self._samples)[-32:]
        return {
            "rule": rule,
            "observed": round(tail[-1][1], 4) if tail else None,
            "low_water": self.low_water,
            "high_water": self.high_water,
            "dwell_s": self.dwell_s,
            "window_s": self.window_s,
            "breaches": self.active_breaches(),
            "samples": [
                [round(t - now, 3), round(h, 4)] for t, h in tail
            ],
        }

    def evidence(self, n_engines: int) -> dict:
        """The full input bundle one decision is judged on — every value
        ALREADY in its stamped (rounded, JSON-safe) form, because
        decide() computes the action FROM this dict via the pure
        `telemetry/audit.py policy_action`: what the audit replays is
        what the policy saw, bit for bit, by construction."""
        now = self._clock()
        self._prune(now)
        tail = self._samples[-1] if self._samples else None
        fc = None
        if self._forecast is not None:
            fc = {
                "predicted": self._forecast.get("predicted"),
                "forecast_abs_err": self._forecast.get("forecast_abs_err"),
                "horizon_s": self._forecast.get("horizon_s"),
                "trend_per_s": self._forecast.get("trend_per_s"),
                "t": self._forecast.get("t"),
            }
        ev = {
            "n_engines": int(n_engines),
            "min_engines": self.min_engines,
            "max_engines": self.max_engines,
            "breaches": sorted({rule for _, rule in self._breaches}),
            "headroom": round(tail[1], 4) if tail else None,
            "low_water": self.low_water,
            "high_water": self.high_water,
            "dwell_s": self.dwell_s,
            "below_held_s": (
                round(now - self._below_since, 6)
                if self._below_since is not None else None
            ),
            "above_held_s": (
                round(now - self._above_since, 6)
                if self._above_since is not None else None
            ),
            "anticipatory": self.anticipatory,
            "target_utilization": self.target_utilization,
            "forecast": fc,
            "lead_time_ms": self._lead_time_ms,
            "lead_quantile": self._lead_quantile,
            "fleet_service_rate_rps": (
                round(self._service_rate_rps, 4)
                if self._service_rate_rps is not None else None
            ),
        }
        if self.low_classes:
            # Stamped ONLY when SLO classes are declared: a classless
            # fleet's evidence bundle stays byte-identical to v10. The
            # pure policy function reads "low_classes" to drop
            # non-binding breaches; "class_weights" is audit-side
            # evidence for the weighted regret score.
            ev["low_classes"] = sorted(self.low_classes)
            if self.class_weights is not None:
                ev["class_weights"] = dict(
                    sorted(self.class_weights.items())
                )
        return ev

    def decide(self, n_engines: int) -> Optional[dict]:
        """The next fleet action at the current signals, or None. Clamped
        to [min_engines, max_engines]; silent inside the cooldown.

        Returns {"action", "signal", "evidence"}: the action comes from
        the pure policy function applied to the evidence bundle decide()
        is about to stamp — reactive semantics are the PR 14 contract
        verbatim when the anticipatory inputs are absent or unmatured,
        and the audit CLI replays the same function on the JSONL."""
        from glom_tpu.telemetry.audit import (
            anticipated_deficit, binding_breaches, policy_action,
        )

        now = self._clock()
        self._prune(now)
        if (
            self._last_action_t is not None
            and now - self._last_action_t < self.cooldown_s
        ):
            return None
        ev = self.evidence(n_engines)
        action = policy_action(ev)
        if action is None:
            return None
        if action == "scale_out":
            # The trigger rule names a BINDING breach: a low-class
            # breach cannot be the reason a decision spent hardware.
            breaches = binding_breaches(ev)
            below = (
                ev["below_held_s"] is not None
                and ev["below_held_s"] >= self.dwell_s
            )
            if breaches:
                rule = breaches[0]
            elif below:
                rule = "headroom"
            else:
                rule = "forecast"
                deficit = anticipated_deficit(ev)
                if deficit is not None:
                    ev["anticipated_deficit_rps"] = deficit
        else:
            rule = "headroom"
        return {
            "action": action,
            "signal": self._signal(now, rule),
            "evidence": ev,
        }

    def acted(self, action: str) -> None:
        now = self._clock()
        self._last_action_t = now
        self._last_action = action
        # The fleet changed shape: both dwell conditions must re-earn
        # their hold from scratch under the NEW capacity.
        self._below_since = None
        self._above_since = None

    @staticmethod
    def pick_drain_target(capacity_records: List[dict]) -> Optional[str]:
        """The least-loaded drainable engine: max headroom among records
        whose stamped state is "ok" (never a draining, probation, or
        dead engine). Ties break on name for determinism."""
        eligible = [
            c for c in capacity_records
            if c.get("state") == "ok"
            and isinstance(c.get("headroom"), (int, float))
        ]
        if not eligible:
            return None
        best = max(eligible, key=lambda c: (c["headroom"], c["engine"]))
        return best["engine"]


def resolve_policy(scfg, *, clock=time.monotonic) -> ElasticPolicy:
    """The one ServeConfig -> policy resolution (the ladder pattern).
    Declared SLO classes arm the QoS extension: the first class in the
    shed order becomes non-binding for elastic decisions and the class
    weights ride every evidence bundle."""
    low_classes: frozenset = frozenset()
    class_weights = None
    if getattr(scfg, "slo_classes", None):
        from glom_tpu.serve.qos import resolve_slo_classes

        spec = resolve_slo_classes(scfg)
        if spec is not None:
            low_classes = spec.low_classes()
            class_weights = spec.weights()
    return ElasticPolicy(
        min_engines=scfg.min_engines,
        max_engines=scfg.max_engines,
        low_water=scfg.elastic_low_water,
        high_water=scfg.elastic_high_water,
        dwell_s=scfg.elastic_dwell_s,
        cooldown_s=scfg.elastic_cooldown_s,
        window_s=scfg.elastic_window_s,
        anticipatory=getattr(scfg, "elastic_anticipatory", False),
        target_utilization=getattr(
            scfg, "elastic_target_utilization", 0.8
        ),
        low_classes=low_classes,
        class_weights=class_weights,
        clock=clock,
    )


class Autoscaler:
    """The supervised control loop around one DynamicBatcher.

    `engine_factory()` must return a NOT-yet-registered engine replica
    (fresh name, own device group/mesh when configured) — the scaler
    runs its full `warmup()` precompile before the batcher ever sees it.
    `spawn_hook` is the chaos seam (resilience/faults.spawn_fault):
    called once per spawn attempt with {"attempt", "n_engines"}; a raise
    there — or anywhere in factory/warmup — is a failed scale-out and
    rolls back loudly. `rules` arms the in-process SLO monitor's
    upper-bound triggers (e.g. {"p99_ms": 250.0, "shed_rate": 0.05});
    the headroom low/high-water signal always rides the capacity
    records directly.

    Use as a context manager (or start()/stop()); `tick()` is public so
    the fake-clock tests drive one evaluation without any thread."""

    def __init__(
        self,
        batcher,
        engine_factory: Callable[[], object],
        *,
        policy: Optional[ElasticPolicy] = None,
        rules: Optional[Dict[str, float]] = None,
        writer=None,
        interval_s: float = 0.5,
        spawn_hook=None,
        warm_degraded_iters: Optional[int] = None,
        forecast=None,
        warm_pool: int = 0,
        fleet: str = "fleet0",
        clock=time.monotonic,
    ):
        from glom_tpu.telemetry.aggregate import SLOMonitor

        if interval_s <= 0:
            raise ValueError(f"interval_s {interval_s} must be > 0")
        if warm_pool < 0:
            raise ValueError(f"warm_pool {warm_pool} must be >= 0")
        self.batcher = batcher
        self.engine_factory = engine_factory
        scfg = getattr(batcher.engine, "scfg", None)
        if policy is None:
            if scfg is None:
                policy = ElasticPolicy(clock=clock)
            else:
                policy = resolve_policy(scfg, clock=clock)
        self.policy = policy
        self.writer = writer
        self.interval_s = interval_s
        self.spawn_hook = spawn_hook
        self.warm_degraded_iters = warm_degraded_iters
        # The live forecast glue (telemetry/forecast.py ForecastEmitter,
        # tapped into the batcher's event stream by the caller): each
        # tick pulls its latest closed-window load forecast and the
        # spawn-lead-time quantile into the policy. None = the policy's
        # anticipatory inputs stay dark (reactive semantics).
        self.forecast = forecast
        self.warm_pool = int(warm_pool)
        self.fleet = str(fleet)
        self._clock = clock
        self.monitor = SLOMonitor(
            dict(rules or {}),
            window_s=policy.window_s,
            writer=writer,
            clock=clock,
        )
        # The batcher's event tap feeds the monitor every emitted serve
        # record (resolve leaves, sheds) — the autoscaler sees the same
        # stream `telemetry watch` would tail, in process, with no file.
        batcher.add_event_tap(self.monitor.observe)
        batcher.attach_elastic(self)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Counters + the fleet timeline, guarded by one lock: the control
        # thread writes, record()/summary readers snapshot.
        self._lock = threading.Lock()
        self._t0 = clock()
        self._decision_seq = 0
        self._last_decision_id: Optional[int] = None
        self._spawn_attempts = 0
        self.n_scale_outs = 0
        self.n_scale_ins = 0
        self.n_spawn_failures = 0
        self.n_ticks = 0
        self.n_decisions = 0
        self.decisions_late = 0
        self.spawn_lead_violations = 0
        self.n_migrated_sessions = 0
        self.n_invalidated_sessions = 0
        self.migrated_bytes = 0
        self._spawn_ms: List[float] = []
        # Warm-pool spares: pre-spawned, fully-warmed engines held
        # OUTSIDE the batcher (never registered — a spare is not a husk
        # and serves no traffic) until a scale-out promotes one.
        self._spares: List[object] = []
        self._spare_spawn_ms: List[float] = []
        self.n_promotions = 0
        self.n_demotions = 0
        self._timeline: List[list] = [
            [0.0, batcher.n_active_engines()]
        ]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None or not self._thread.is_alive():
            self.fill_warm_pool()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="glom-serve-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def fill_warm_pool(self) -> int:
        """Pre-spawn spares up to `warm_pool` (factory + FULL warmup,
        exactly the scale-out build), held outside admission. Runs
        before the control thread starts — provisioning happens before
        traffic, and each spare's spawn_ms is REAL lead-time evidence
        (the "spare_spawn" event feeds ForecastEmitter's lead model),
        so the anticipatory signal can arm before the first live
        scale-out. A failed spare spawn is stamped and stops the fill —
        the fleet runs with the spares it has."""
        n_built = 0
        while True:
            with self._lock:
                if len(self._spares) >= self.warm_pool:
                    return n_built
                n_spares = len(self._spares)
            t0 = self._clock()
            try:
                engine = self.engine_factory()
                warmup = getattr(engine, "warmup", None)
                if callable(warmup):
                    warmup()
                    if self.warm_degraded_iters is not None:
                        warmup(iters_override=self.warm_degraded_iters)
            except BaseException as e:  # noqa: BLE001 — stamped, fill stops
                self._emit(
                    {
                        "event": "spawn_rollback",
                        "decision_id": None,
                        "fleet": self.fleet,
                        "spare": True,
                        "n_engines": self.batcher.n_active_engines(),
                        "exception": f"{type(e).__name__}: {e}"[:300],
                    }
                )
                return n_built
            spawn_ms = round(1e3 * (self._clock() - t0), 3)
            with self._lock:
                self._spares.append(engine)
                self._spare_spawn_ms.append(spawn_ms)
                n_spares = len(self._spares)
            n_built += 1
            self._emit(
                {
                    "event": "spare_spawn",
                    "fleet": self.fleet,
                    "engine": getattr(engine, "name", None),
                    "spawn_ms": spawn_ms,
                    "n_spares": n_spares,
                    "n_engines": self.batcher.n_active_engines(),
                }
            )

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=60.0)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        # Supervised: one tick's exception is stamped evidence, never the
        # loop's death — a control plane that silently stops controlling
        # is the failure mode this file exists to not have.
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except BaseException as e:  # noqa: BLE001 — stamped, loop lives
                self._emit(
                    {
                        "error": "autoscaler-tick",
                        "value": None,
                        "note": f"{type(e).__name__}: {e}"[:300],
                    },
                    kind="error",
                )

    # -- the control tick --------------------------------------------------

    def tick(self) -> Optional[dict]:
        """One evaluation: capacity -> signals -> policy -> (maybe) act.
        Returns the decision taken, or None."""
        caps = self.batcher.capacity_records()
        for c in caps:
            # Live capacity on the stream each tick (the summary-only
            # cadence is too coarse for a watch tailing the scale loop)
            # and into the monitor (which skips probation/draining
            # headroom — the capacity-record contract).
            self._emit(c, kind=None)
            self.monitor.observe(c)
        eligible = [
            c["headroom"] for c in caps
            if c.get("state") == "ok"
            and isinstance(c.get("headroom"), (int, float))
        ]
        if eligible:
            self.policy.observe_headroom(min(eligible))
        # The capacity side of the anticipated deficit: the fleet's
        # measured ok-engine service rate, refreshed every tick.
        rates = [
            c["service_rate_rps"] for c in caps
            if c.get("state") == "ok"
            and isinstance(c.get("service_rate_rps"), (int, float))
        ]
        self.policy.note_service_rate(sum(rates) if rates else None)
        if self.forecast is not None:
            self.policy.note_forecast(self.forecast.latest_forecast())
            lead_model = self.forecast.lead_model
            self.policy.note_lead_time(
                lead_model.lead_time_ms(), lead_model.quantile
            )
        for b in self.monitor.evaluate():
            # Lower-bound rules (headroom) are the policy's OWN water
            # marks — only upper-bound breaches (p99, shed_rate) feed
            # the breach-precedence signal.
            if b.get("bound") != "lower":
                self.policy.note_breach(b["rule"])
        with self._lock:
            self.n_ticks += 1
        n = self.batcher.n_active_engines()
        decision = self.policy.decide(n)
        if decision is None:
            return None
        if decision["action"] == "scale_out":
            self._scale_out(n, decision["signal"], decision.get("evidence"))
        else:
            self._scale_in(
                n, decision["signal"], caps, decision.get("evidence")
            )
        return decision

    def _mint_decision(
        self, action: str, evidence: Optional[dict]
    ) -> int:
        """Mint the next decision_id and stamp the schema-v10 "decision"
        record — the evidence bundle, the action the pure policy
        function derived from it, and the chain link to the previous
        decision. Every actuation event that follows carries this id."""
        from glom_tpu.telemetry.audit import binding_breaches

        with self._lock:
            self._decision_seq += 1
            decision_id = self._decision_seq
            prev = self._last_decision_id
            self._last_decision_id = decision_id
            self.n_decisions += 1
            if (
                action == "scale_out"
                and isinstance(evidence, dict)
                and binding_breaches(evidence)
            ):
                # Scaled AFTER the SLO already broke — the reactive
                # failure mode the anticipatory signal exists to avoid.
                self.decisions_late += 1
        self._emit(
            {
                "t": round(self._clock() - self._t0, 3),
                "fleet": self.fleet,
                "decision_id": decision_id,
                "prev_decision_id": prev,
                "action": action,
                "evidence": evidence,
            },
            kind="decision",
        )
        return decision_id

    def _note_fleet(self, n: int) -> None:
        with self._lock:
            self._timeline.append(
                [round(self._clock() - self._t0, 3), n]
            )

    def _scale_out(
        self, n: int, signal: dict, evidence: Optional[dict] = None
    ) -> None:
        decision_id = self._mint_decision("scale_out", evidence)
        self._emit(
            {
                "event": "scale_out_decision",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "n_engines": n,
                "signal": signal,
            }
        )
        # A warm spare absorbs the scale-out at ~0 spawn cost: promote
        # it (register with the batcher) instead of building cold.
        with self._lock:
            spare = self._spares.pop(0) if self._spares else None
        if spare is not None:
            self._promote_spare(spare, decision_id, n)
            return
        with self._lock:
            self._spawn_attempts += 1
            attempt = self._spawn_attempts
        t0 = self._clock()
        try:
            if self.spawn_hook is not None:
                self.spawn_hook({"attempt": attempt, "n_engines": n})
            engine = self.engine_factory()
            # The FULL precompile, off the hot path: every bucket
            # signature (and the ladder's degraded route when armed)
            # compiles before admission can open. A fake engine without
            # warmup() is the policy tests' no-op.
            warmup = getattr(engine, "warmup", None)
            if callable(warmup):
                warmup()
                if self.warm_degraded_iters is not None:
                    warmup(iters_override=self.warm_degraded_iters)
        except BaseException as e:  # noqa: BLE001 — rollback is the contract
            # FAILED scale-out: no registration, loud evidence, cooldown
            # still charged (a persistently failing spawn must not retry
            # every tick at full speed).
            with self._lock:
                self.n_spawn_failures += 1
            self.policy.acted("spawn_rollback")
            self._emit(
                {
                    "event": "spawn_rollback",
                    "decision_id": decision_id,
                    "fleet": self.fleet,
                    "n_engines": n,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                }
            )
            return
        spawn_ms = round(1e3 * (self._clock() - t0), 3)
        name = self.batcher.add_engine(
            engine,
            detail={"decision_id": decision_id, "fleet": self.fleet},
        )
        # Did the spawn land inside the lead the decision believed? A
        # violation means the anticipatory act-ahead margin was too
        # short — the audit counts these against the lead-time model.
        lead_ms = (
            evidence.get("lead_time_ms")
            if isinstance(evidence, dict) else None
        )
        violation = (
            isinstance(lead_ms, (int, float)) and spawn_ms > lead_ms
        )
        with self._lock:
            self.n_scale_outs += 1
            self._spawn_ms.append(spawn_ms)
            if violation:
                self.spawn_lead_violations += 1
        self.policy.acted("scale_out")
        self._note_fleet(n + 1)
        rec = {
            "event": "scale_out",
            "decision_id": decision_id,
            "fleet": self.fleet,
            "engine": name,
            "spawn_ms": spawn_ms,
            "n_engines": n + 1,
            "signal": signal,
        }
        if violation:
            rec["lead_violation"] = True
            rec["lead_time_ms"] = lead_ms
        self._emit(rec)
        # Admission is OPEN from add_engine's worker start — stamped as
        # its own transition so the chaos chain check can pin the order:
        # decision -> (warmup inside spawn_ms) -> admission.
        self._emit(
            {
                "event": "admission_open",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "engine": name,
                "n_engines": n + 1,
            }
        )

    def _promote_spare(self, engine, decision_id: int, n: int) -> None:
        """Register a pre-warmed spare with the batcher — the ~0-cost
        scale-out path. A demoted spare's old name lives on in the
        batcher as a drained husk (the evidence of its drain), so a
        re-promotion takes a fresh suffixed name."""
        t0 = self._clock()
        base = getattr(engine, "name", None) or "spare"
        name = base
        k = 0
        while name in getattr(self.batcher, "_engine_state", {}):
            k += 1
            name = f"{base}~p{k}"
        if name != base:
            try:
                engine.name = name
            except AttributeError:
                pass
        name = self.batcher.add_engine(
            engine,
            name=name,
            detail={
                "decision_id": decision_id,
                "fleet": self.fleet,
                "spare": True,
            },
        )
        promote_ms = round(1e3 * (self._clock() - t0), 3)
        with self._lock:
            self.n_promotions += 1
            n_spares = len(self._spares)
        self.policy.acted("scale_out")
        self._note_fleet(n + 1)
        self._emit(
            {
                "event": "spare_promote",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "engine": name,
                "promote_ms": promote_ms,
                "n_spares": n_spares,
                "n_engines": n + 1,
            }
        )
        self._emit(
            {
                "event": "admission_open",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "engine": name,
                "n_engines": n + 1,
            }
        )

    def _scale_in(
        self,
        n: int,
        signal: dict,
        caps: List[dict],
        evidence: Optional[dict] = None,
    ) -> None:
        target = self.policy.pick_drain_target(caps)
        if target is None:
            return
        decision_id = self._mint_decision("scale_in", evidence)
        self._emit(
            {
                "event": "scale_in_decision",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "engine": target,
                "n_engines": n,
                "signal": signal,
            }
        )
        # Resolve the engine object BEFORE the drain: husk retention
        # (husk_max=0) may retire the name from the batcher's registry
        # inside drain_engine, and a retired husk must still be able to
        # demote into the warm pool — the spare outlives its husk.
        engine = self.batcher.engine_by_name(target)
        try:
            stats = self.batcher.drain_engine(
                target,
                detail={"decision_id": decision_id, "fleet": self.fleet},
            )
        except ValueError as e:
            # Raced a death/concurrent drain: the fleet can no longer
            # spare the target — stamped, no action, cooldown charged.
            self.policy.acted("drain_abort")
            self._emit(
                {
                    "event": "drain_abort",
                    "decision_id": decision_id,
                    "fleet": self.fleet,
                    "engine": target,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                }
            )
            return
        # Demote into the warm pool instead of releasing when the pool
        # is below target: the drained engine keeps its device state and
        # compiled executables, so the NEXT scale-out promotes it at ~0
        # cost. Otherwise release as before.
        demote = False
        if engine is not None:
            with self._lock:
                if len(self._spares) < self.warm_pool:
                    self._spares.append(engine)
                    self.n_demotions += 1
                    demote = True
                    n_spares = len(self._spares)
        if not demote:
            release = getattr(engine, "release", None)
            if callable(release):
                release()
        with self._lock:
            self.n_scale_ins += 1
            self.n_migrated_sessions += stats.get("n_migrated", 0)
            self.n_invalidated_sessions += stats.get("n_invalidated", 0)
            self.migrated_bytes += stats.get("bytes_migrated", 0)
        self.policy.acted("scale_in")
        self._note_fleet(n - 1)
        self._emit(
            {
                "event": "drain_release",
                "decision_id": decision_id,
                "fleet": self.fleet,
                "engine": target,
                "n_engines": n - 1,
                "demoted": demote,
                **{
                    k: stats.get(k)
                    for k in (
                        "n_migrated", "n_invalidated", "bytes_migrated",
                        "flush_ok",
                    )
                },
            }
        )
        if demote:
            self._emit(
                {
                    "event": "spare_demote",
                    "decision_id": decision_id,
                    "fleet": self.fleet,
                    "engine": target,
                    "n_spares": n_spares,
                    "n_engines": n - 1,
                }
            )

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict, kind: Optional[str] = "serve") -> None:
        from glom_tpu.tracing.flight import write_or_observe

        if kind is None:
            # Already-stamped records (the capacity rollup) pass through.
            write_or_observe(self.writer, rec)
            return
        if kind in ("serve", "decision"):
            stamped = rec
            if kind == "serve":
                from glom_tpu.serve.events import emit_serve

                stamped = emit_serve(self.writer, rec)
            else:
                stamped = schema.stamp(rec, kind="decision")
                write_or_observe(self.writer, stamped)
            # Scale events AND decision records join the batcher's tap
            # fan-out: the forecast emitter's spawn-lead-time model
            # (telemetry/forecast.py) reads spawn_ms from the same
            # in-process stream `telemetry watch` would tail — the
            # scale_out record must not exist only on disk. Taps never
            # kill the control loop.
            for tap in list(getattr(self.batcher, "_taps", ())):
                try:
                    tap(stamped)
                except Exception:  # noqa: BLE001
                    pass
            return
        write_or_observe(self.writer, schema.stamp(rec, kind=kind))

    def record(self) -> dict:
        """The `elastic` summary nest (serve/batcher.summary_record nests
        it; `telemetry compare` flattens it as serve_elastic.* rows with
        spawn latency and migration bytes classified as costs)."""
        with self._lock:
            spawn_ms = list(self._spawn_ms)
            spare_spawn_ms = list(self._spare_spawn_ms)
            rec = {
                "n_scale_outs": self.n_scale_outs,
                "n_scale_ins": self.n_scale_ins,
                "n_spawn_failures": self.n_spawn_failures,
                "n_ticks": self.n_ticks,
                # The decision observatory's runtime counters (the audit
                # recomputes all three from the JSONL independently):
                # decisions_late = scale-outs decided while a breach was
                # already live; spawn_lead_violations = spawns slower
                # than the lead the decision believed. `telemetry
                # compare` classifies every one a cost.
                "n_decisions": self.n_decisions,
                "decisions_late": self.decisions_late,
                "spawn_lead_violations": self.spawn_lead_violations,
                # Warm-pool spares (a spare is NOT a husk: it was never
                # registered with the batcher, serves no traffic, and
                # husk retention cannot touch it).
                "warm_pool": self.warm_pool,
                "n_spares": len(self._spares),
                "n_promotions": self.n_promotions,
                "n_demotions": self.n_demotions,
                "spare_spawn_ms_mean": (
                    round(sum(spare_spawn_ms) / len(spare_spawn_ms), 3)
                    if spare_spawn_ms else None
                ),
                "n_migrated_sessions": self.n_migrated_sessions,
                "n_invalidated_sessions": self.n_invalidated_sessions,
                "migrated_bytes": self.migrated_bytes,
                "spawn_ms_mean": (
                    round(sum(spawn_ms) / len(spawn_ms), 3)
                    if spawn_ms else None
                ),
                "spawn_ms_max": max(spawn_ms) if spawn_ms else None,
                # The RAW spawn latencies, in spawn order: the lead-time
                # model (telemetry/forecast.py SpawnLeadTimeModel) fits
                # its percentile from these, not from the mean/max pair.
                "spawn_ms": spawn_ms,
                "n_engines": self.batcher.n_active_engines(),
                "n_engines_peak": max(n for _, n in self._timeline),
                # The fleet-size timeline ([t_rel_s, n_engines] per
                # change): the bench's n_engines row and perfetto's
                # counter track both read it.
                "timeline": [list(e) for e in self._timeline],
            }
        return rec
