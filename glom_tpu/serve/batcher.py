"""Host-side dynamic batching: bounded queue, bucket padding, continuation
queue, multi-engine fan-out, shed path.

TPU serving economics are batch economics: one column-update of a batch-8
bucket costs barely more than batch-1 (the MXU is latency-bound at tiny
batches), so the host's job is to GATHER concurrent requests into bucket
shapes without letting the gathering itself become the latency. The
classic admission policy does it with two knobs:

  * max_batch — dispatch the moment this many requests are waiting (the
    throughput ceiling; never above the engine's largest bucket);
  * max_delay_ms — dispatch anyway once the OLDEST waiting request has
    aged this long (the latency floor: a lone 3am request pays at most
    max_delay_ms of gathering, not forever).

Gathered requests pad up to the smallest admitting bucket (the engine only
ever sees precompiled shapes — no mid-traffic recompiles) with a validity
mask, so pad rows neither reach callers nor vote on the consensus
early-exit witness (serve/early_exit).

TWO-TIER EARLY EXIT (ServeConfig.max_continuations > 0, auto route): a
bucket exits when its fastest quorum converges (exit_quorum); rows still
unconverged at exit are STRAGGLERS — their warm column state re-buckets
into the continuation queue as one group per dispatch, carrying the
remaining per-request budget, and workers drain that queue ahead of fresh
traffic (stragglers are the oldest requests in the system). Per-request
early exit wins without dynamic shapes: every compiled program still has
a static bucket and budget; what varies is which program a request's NEXT
hop runs. Ticket conservation holds across hops — a request resolves
exactly once, with the SUM of its dispatches' executed iterations.

MIXED WARM/COLD BUCKETS: a dispatch is built row by row — each row is
cold (the forward's own init), warm from the SESSION CACHE, or warm as a
continuation straggler — via a per-row `levels0` select (cold rows ride
the engine's `cold_levels()`, bitwise the init the forward would build
itself). A continuation group therefore FOLDS waiting fresh traffic into
its bucket's pad slots instead of dispatching alone, and the auto route's
budget caps at the tightest row's remainder (rows capped short of their
own budget simply re-enter the continuation queue with the difference).

STREAMING (ServeConfig.column_cache_bytes > 0, serve/column_cache.py):
submit(img, session_id=...) marks a request as one frame of a stream. At
dispatch the worker warm-starts the row from the session's cached
converged columns (hit/miss stamped on the dispatch record); on resolve
the new converged columns write back under the key, LRU-evicted under
the HBM-priced byte budget and TTL-expired when the stream goes quiet. A
dispatch failure invalidates the failing engine's entries BEFORE any
requeue, so stale or dead-engine state never warm-starts a request.

ENGINE REJOIN (ServeConfig.rejoin_threshold > 0): a dead engine's worker
hands off to a probation thread that health-dispatches the smallest
bucket until N CONSECUTIVE successes re-admit the engine (stamped
engine_probation / engine_rejoin events); a failed probe restarts the
count. 0 keeps death terminal until restart — the pre-rejoin contract.

MULTI-ENGINE FAN-OUT (engines=[...]): one worker thread per engine pulls
from the SHARED admission queue — least-queue-depth dispatch by
construction (an idle engine takes the next batch; a busy one doesn't
pull). A dispatch failure on one engine re-dispatches its requests to the
siblings (bounded per-request redispatch budget), and an engine whose
failures persist is marked DEAD — its worker exits, its queued work
drains to the survivors, and the stamped engine_failover/engine_dead
events let a chaos run reconcile the hand-off (docs/RESILIENCE.md,
kill-serve). The PR 6 ladder/retry machinery operates PER ENGINE: each
engine keeps its own RetryPolicy, and with ServeConfig.ladder each gets
its own DegradationLadder (admission sheds only when every live engine's
ladder is on its shed rung).

LOCK ORDER (the lock-ORDER cycle checker in glom_tpu/analysis/lockset.py
gates this file): `_engine_lock` is always acquired BEFORE
`_counter_lock`, never the reverse — the per-engine dispatch bookkeeping
and the global conservation counters must move together (a summary that
read one without the other could see served work on a dead engine), so
the counter update nests inside the engine-state update.

Failure discipline (the PR 2/3 lesson — a wedged backend must fail FAST
and leave evidence, never hang):

  * the request queue is BOUNDED: a submit against a full queue sheds
    immediately with QueueFullError (backpressure to the caller, who can
    retry/downgrade) and a stamped "serve" shed event carrying the WHY
    (queue depth/capacity, ladder rung);
  * when the global backend watchdog says "down", submissions and any
    already-gathered requests fail fast with BackendDownError, and each
    emits a schema "error" record carrying the machine-readable cause. A
    FLAPPING backend is NOT down: it keeps serving (degraded via the
    ladder; dispatch failures retry per the engine's RetryPolicy);
  * a dispatch exception with no sibling engine fails ONLY that batch's
    requests (each ticket re-raises it) and the worker keeps serving;
  * with a DegradationLadder attached, pressure and flap step serving
    DOWN one reversible rung at a time — shedding is the floor of the
    ladder, not the only move.

ELASTIC FLEET (serve/elastic.py, docs/SERVING.md "Elastic serving"): the
fleet is no longer fixed at construction — `add_engine()` registers a
fully-warmed replica at runtime (admission opens the moment its worker
starts), and `drain_engine()` runs the graceful scale-in state machine:
DRAINING is a first-class engine state distinct from dead (a draining
worker stops pulling new work, finishes its in-flight dispatch, hands its
affinity queue back to the shared queue, and exits — never into
probation), the engine's cache sessions migrate to a sibling pool (or
invalidate with a stamped `drain` reason), and the engine leaves the
fleet as DRAINED — excluded from capacity records (a permanent 0.0
headroom would re-trigger the very autoscaler that drained it) but
retained in the summary's engines nest as evidence. With no autoscaler
attached none of this machinery runs and the static fleet is
byte-for-byte the pre-elastic contract.

Host phases ride tracing.spans (SERVE_PHASES: serve_enqueue, serve_batch,
serve_dispatch, serve_fetch), aggregated per phase and drained by
span_records() — the same <1%-overhead rollup form the fit loop uses.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from glom_tpu.telemetry import schema, tracectx
from glom_tpu.tracing.spans import SpanAggregator, span


class ShedError(RuntimeError):
    """Base of the fast-fail admission errors (never a hang). `detail`
    carries the machine-readable why (queue depth, ladder rung) — the
    same fields the stamped shed record gets, so a caller's except block
    and the telemetry stream read one story."""

    def __init__(self, message: str, **detail):
        super().__init__(message)
        self.detail = detail


class QueueFullError(ShedError):
    """Bounded queue at capacity: backpressure, retry later."""


class BackendDownError(ShedError):
    """The backend watchdog reports the accelerator down."""


class LadderShedError(ShedError):
    """The degradation ladder's last rung: every cheaper serving mode is
    already exhausted (glom_tpu/resilience/ladder.py)."""


class Ticket:
    """One request's future: result() blocks until served or failed.

    `trace_id`/`span_id` are the request's minted trace context
    (telemetry/tracectx.py; None when ServeConfig.trace_requests is off):
    trace_id names the request's causal tree across every hop it rides,
    span_id is the submit root every first-hop record parents to. After
    resolve, `hops` and `dispatch_ms` carry the served totals the trace
    tree's conservation check reconciles against."""

    def __init__(self, request_id, trace_id=None, span_id=None,
                 slo_class=None):
        self.request_id = request_id
        self.trace_id = trace_id
        self.span_id = span_id
        # The request's SLO class (glom_tpu/serve/qos.py; None =
        # unclassed / classless config): stamped on every record this
        # request leaves — admit, shed, settle, resolve — so per-tenant
        # conservation reconciles from the stream alone (schema v11).
        self.slo_class = slo_class
        self.hops: Optional[int] = None
        self.dispatch_ms: Optional[float] = None
        self._done = threading.Event()
        self._levels: Optional[np.ndarray] = None
        self._iters_run: Optional[int] = None
        self._latency_s: Optional[float] = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        # Armed by the workload observatory (enable_admission_events):
        # called EXACTLY once with (ticket, "served"|"failed") at the
        # terminal, whichever path got there — the single choke point
        # covering resolve, redispatch exhaustion, and stop()-drain, so
        # the recorder never needs a per-failure-site event.
        self._settle_cb = None

    def _settled(self, outcome: str) -> None:
        cb = self._settle_cb
        if cb is None:
            return
        self._settle_cb = None  # terminal states are terminal
        try:
            cb(self, outcome)
        except Exception:  # noqa: BLE001 — evidence never kills a worker
            pass

    def _resolve(self, levels, iters_run, hops=None, dispatch_ms=None):
        self._levels = levels
        self._iters_run = iters_run
        self.hops = hops
        self.dispatch_ms = dispatch_ms
        self._latency_s = time.perf_counter() - self.t_submit
        self._done.set()
        self._settled("served")

    def _fail(self, exc: BaseException):
        self._error = exc
        self._latency_s = time.perf_counter() - self.t_submit
        self._done.set()
        self._settled("failed")

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """(levels [n, L, d], iters_run, latency_s) for THIS request, or
        re-raises the failure. latency_s is submit-to-resolve wall time —
        queueing + gathering + dispatch(es) + fetch, the number the user
        felt; iters_run is the TOTAL executed column iterations across
        every hop the request rode (initial dispatch + continuations)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not served within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._levels, self._iters_run, self._latency_s


class _Item:
    """One request's dispatch-side state, COLD or WARM in one shape (the
    per-row `levels0` select needs rows of both kinds in one batch):

      * cold — `levels is None`: the forward builds its own init;
      * warm from the SESSION CACHE — `warm_src == "cache"`: levels is
        the stream's previous converged state, full budget remains;
      * warm as a CONTINUATION straggler — `warm_src == "cont"`: levels
        is this request's own mid-flight state, `executed` iterations
        already run, `hops` continuation dispatches taken.

    The image rides every hop (tokens are recomputed — they are noise vs
    one iteration); `redispatches` counts engine-failover hand-offs.
    `parent_span` is the span this item's NEXT record parents to — the
    submit root initially, then the last dispatch/failover span it rode;
    `dispatch_ms` accumulates the rounded per-hop dispatch latencies so
    the resolve leaf's total reconciles EXACTLY with the hop records."""

    __slots__ = (
        "img", "ticket", "session", "levels", "executed", "hops",
        "redispatches", "warm_src", "parent_span", "dispatch_ms",
        "n_patches", "pages", "patches", "t_enq", "phase_ms",
        "slo_class",
    )

    def __init__(
        self, img: np.ndarray, ticket: Ticket, session=None,
        n_patches: Optional[int] = None,
    ):
        self.img = img
        self.ticket = ticket
        self.session = session
        self.levels: Optional[np.ndarray] = None
        self.executed = 0  # column iterations run so far
        self.hops = 0      # continuation dispatches so far
        self.redispatches = 0
        # None | "cache" (host array) | "cont" (straggler) | "pages"
        # (device-resident pool pages — serve/paged_columns.py)
        self.warm_src: Optional[str] = None
        self.parent_span = ticket.span_id
        self.dispatch_ms = 0.0
        self.n_patches = n_patches  # ragged: this row's patch count
        self.pages = None           # pages-warm: the pinned PageHit
        self.patches = None         # delta mode: host-patchified input
        # When this item last ENTERED a queue (batcher clock): the
        # dispatch phase split's queue_wait anchor — reset on every
        # re-enqueue (continuation, failover requeue), so each hop's
        # queue_wait measures ITS OWN wait, not the request's lifetime.
        self.t_enq = 0.0
        # Per-phase accumulation across hops (the rounded per-hop values,
        # in hop order) — the resolve leaf's phase_ms_total, conserved
        # bit-exactly by `telemetry trace` (tracectx.PHASE_KEYS).
        self.phase_ms: dict = {}
        # The ticket's SLO class, mirrored on the item so the class
        # scheduler routes requeues/continuations without touching the
        # ticket (glom_tpu/serve/qos.py; None = classless).
        self.slo_class = ticket.slo_class


def _backend_down() -> bool:
    from glom_tpu.telemetry.watchdog import backend_record

    return backend_record().get("backend_state") == "down"


def _patchify_host(img: np.ndarray, patch_size: int) -> np.ndarray:
    """[c, H, W] -> [n, p*p*c], bit-identical to ops/patch.patchify's
    einops order ('b c (h p1) (w p2) -> b (h w) (p1 p2 c)') — a pure
    reshape/transpose with no float ops, so the ragged route's in-graph
    embed sees exactly the values the dense route's patchify produces
    (the bitwise parity anchor; tests/test_paged_columns.py)."""
    c, height, width = img.shape
    p = patch_size
    h, w = height // p, width // p
    x = img.reshape(c, h, p, w, p)
    x = x.transpose(1, 3, 2, 4, 0)  # [h, w, p1, p2, c]
    return np.ascontiguousarray(x.reshape(h * w, p * p * c))


class DynamicBatcher:
    """The admission scheduler in front of one or more InferenceEngines.

    Lifecycle: use as a context manager (or start()/stop()). submit() is
    thread-safe and returns a Ticket; one worker thread PER ENGINE
    gathers, pads, and dispatches from the shared queue. `engine` needs
    .infer(imgs, n_valid) -> ServeResult and .pick_bucket(n) — the tests
    drive the policy with a fake engine, no device required. Pass
    `engines=[...]` (or a list as the first argument) for multi-engine
    fan-out behind one admission queue.
    """

    def __init__(
        self,
        engine=None,
        *,
        engines: Optional[List] = None,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        writer=None,
        shed_when_down: bool = True,
        ladder=None,
        engine_fail_threshold: int = 2,
        max_redispatch: int = 2,
        column_cache=None,
        rejoin_threshold: Optional[int] = None,
        rejoin_interval_ms: Optional[float] = None,
        trace: Optional[bool] = None,
        phase_split: Optional[bool] = None,
        clock=time.perf_counter,
    ):
        if (engine is None) == (engines is None):
            raise ValueError("exactly one of engine= or engines=[...]")
        if engines is None:
            engines = list(engine) if isinstance(engine, (list, tuple)) else [
                engine
            ]
        if not engines:
            raise ValueError("engines must be non-empty")
        self.engines = list(engines)
        self.engine = self.engines[0]  # single-engine compatibility alias
        scfg = getattr(self.engine, "scfg", None)
        self.max_batch = (
            max_batch if max_batch is not None
            else (scfg.max_batch if scfg else 8)
        )
        self.max_delay_s = (
            max_delay_ms if max_delay_ms is not None
            else (scfg.max_delay_ms if scfg else 5.0)
        ) / 1e3
        depth = (
            queue_depth if queue_depth is not None
            else (scfg.queue_depth if scfg else 64)
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch {self.max_batch} must be >= 1")
        if engine_fail_threshold < 1:
            raise ValueError(
                f"engine_fail_threshold {engine_fail_threshold} must be >= 1"
            )
        self.writer = writer
        self.shed_when_down = shed_when_down
        self.engine_fail_threshold = engine_fail_threshold
        self.max_redispatch = max_redispatch
        # Request-scoped tracing (telemetry/tracectx.py): None resolves
        # from the lead engine's ServeConfig (trace_requests, default ON).
        # When off, the trace-context keys still stamp as null — an
        # explicitly UNTRACED record lints; an absent key would not.
        self._trace = (
            trace if trace is not None
            else bool(getattr(scfg, "trace_requests", True)) if scfg else True
        )
        # Latency decomposition (schema v7, docs/OBSERVABILITY.md
        # "Capacity observatory"): every dispatch record splits
        # latency_ms into queue_wait/pack/h2d/device/resolve, summing to
        # it BIT-EXACTLY (latency_ms is DEFINED as the left-to-right
        # float sum of the rounded phase values — tracectx.PHASE_KEYS
        # order), and the per-request resolve leaf accumulates the same
        # values per phase. None resolves from the lead engine's
        # ServeConfig (phase_split, default ON); off stamps the keys as
        # null and latency_ms reverts to the bare engine dispatch wall.
        self._phase_split = (
            phase_split if phase_split is not None
            else bool(getattr(scfg, "phase_split", True)) if scfg else True
        )
        # Page pools (serve/paged_columns.py): engines carrying a device
        # page pool switch the session cache to PAGES mode — entries are
        # page-table references, warm dispatches take pages in-graph,
        # and session AFFINITY routes a stream to the engine holding its
        # pages. Ragged admission (scfg.ragged) packs mixed-resolution
        # requests onto the page axis (docs/SERVING.md).
        self._pools = {
            self._ename(eng, i): eng.pool
            for i, eng in enumerate(self.engines)
            if getattr(eng, "pool", None) is not None
        }
        self._ragged = bool(getattr(scfg, "ragged", False)) if scfg else False
        # Streaming warm-start column cache (serve/column_cache.py):
        # None RESOLVES from the lead engine's ServeConfig
        # (column_cache_bytes > 0 builds one) — the ladder pattern. Pass
        # an explicit ColumnCache to own the knobs/clock (tests do).
        if column_cache is None:
            from glom_tpu.serve.column_cache import resolve_column_cache

            column_cache = resolve_column_cache(
                scfg, writer=writer, pools=self._pools or None
            )
        self.cache = column_cache
        if (
            self.cache is not None
            and getattr(self.cache, "pools", None) is not None
        ):
            # Pages mode must cover the WHOLE fleet: a pool-less engine
            # would receive PageHits its host-path dispatch cannot use
            # (and its write-backs have no pool to land in) — a config
            # error, caught loudly here rather than as a mid-traffic
            # worker crash.
            missing = [
                self._ename(eng, i)
                for i, eng in enumerate(self.engines)
                if self._ename(eng, i) not in self.cache.pools
            ]
            if missing:
                raise ValueError(
                    f"pages-mode column cache but engines {missing} carry "
                    "no page pool — mixed pool/pool-less fleets are "
                    "unsupported (give every engine page_pool_pages, or "
                    "none)"
                )
        # Engine REJOIN after recovery: a dead engine's worker hands off
        # to a PROBATION thread that health-dispatches until
        # rejoin_threshold consecutive successes re-admit the engine
        # (stamped engine_rejoin). 0 (the default) keeps death terminal.
        self._rejoin_threshold = (
            rejoin_threshold if rejoin_threshold is not None
            else (getattr(scfg, "rejoin_threshold", 0) if scfg else 0)
        )
        self._rejoin_interval_s = (
            rejoin_interval_ms if rejoin_interval_ms is not None
            else (getattr(scfg, "rejoin_interval_ms", 200.0) if scfg else 200.0)
        ) / 1e3
        if self._rejoin_threshold < 0:
            raise ValueError(
                f"rejoin_threshold {self._rejoin_threshold} must be >= 0"
            )
        # Degradation ladders (glom_tpu/resilience/ladder.py) — PER
        # ENGINE: each engine's worker feeds its own ladder queue pressure
        # + backend state, a capped_iters-or-worse rung dispatches with
        # the degraded fixed budget, a bucket_cap-or-worse rung gathers
        # smaller batches, and admission sheds only when EVERY live
        # engine's ladder is on its shed rung. ladder=None RESOLVES from
        # each engine's ServeConfig (scfg.ladder=True builds one — a
        # config that asks for the ladder must never be silently
        # two-mode); pass an explicit instance (single-engine only) to
        # own the knobs.
        self._ladders = {}
        for i, eng in enumerate(self.engines):
            name = self._ename(eng, i)
            escfg = getattr(eng, "scfg", None)
            if ladder is not None:
                if len(self.engines) > 1:
                    raise ValueError(
                        "pass ladder= with a single engine only; "
                        "multi-engine ladders resolve per engine from "
                        "ServeConfig.ladder"
                    )
                self._ladders[name] = ladder
            elif (
                escfg is not None
                and getattr(escfg, "ladder", False)
                and getattr(eng, "cfg", None) is not None
            ):
                from glom_tpu.resilience.ladder import DegradationLadder

                self._ladders[name] = DegradationLadder.from_config(
                    eng.cfg, escfg, writer=writer
                )
            else:
                self._ladders[name] = None
        self.ladder = self._ladders[self._ename(self.engines[0], 0)]
        self._clock = clock
        # Multi-tenant QoS (glom_tpu/serve/qos.py, docs/SERVING.md "SLO
        # classes"): a ServeConfig that declares slo_classes swaps the
        # shared FIFO for the deficit-weighted-fair class scheduler —
        # per-class BOUNDED lanes behind the same queue.Queue facade
        # (get/get_nowait/put_nowait/qsize/empty/maxsize), so every
        # gather/requeue/drain path below reads one queue either way. A
        # classless config keeps the plain queue.Queue byte-for-byte
        # (the bit-parity pin, tests/test_qos.py).
        self._qos = None
        if scfg is not None and getattr(scfg, "slo_classes", None):
            from glom_tpu.serve.qos import ClassQueues, resolve_slo_classes

            self._qos = resolve_slo_classes(scfg)
            self._q = ClassQueues(self._qos, default_depth=depth)
        else:
            self._q: queue.Queue = queue.Queue(maxsize=depth)
        # SESSION-AFFINITY queues (pages mode): one per engine. A stream
        # whose pages live in engine E's pool routes to E's queue — its
        # worker drains it ahead of the shared queue, so the warm path
        # actually finds its pages on the engine that holds them. A full
        # affinity queue (or a dead target) falls back to the shared
        # queue: affinity is a fast path, never a trap.
        self._aff_q = {
            self._ename(eng, i): queue.Queue(maxsize=depth)
            for i, eng in enumerate(self.engines)
        }
        # Continuation queue: one GROUP (list of warm _Item sharing a
        # source dispatch) per entry. Unbounded:
        # its population is bounded by admitted-but-unresolved requests,
        # which the admission queue already bounds.
        self._cont_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.spans = SpanAggregator()
        # Per-engine dispatch bookkeeping. LOCK ORDER: _engine_lock
        # before _counter_lock (see module docstring) — the nested
        # acquisition in _note_dispatch/_note_failure is the pattern the
        # lock-order checker verifies stays acyclic.
        self._engine_lock = threading.Lock()
        self._engine_state = {
            self._ename(eng, i): {
                "alive": True,
                "dispatches": 0,
                "consecutive_failures": 0,
                "probation": False,
                "rejoins": 0,
            }
            for i, eng in enumerate(self.engines)
        }
        # Counters for the end-of-run summary record. n_requests counts
        # every submit() ATTEMPT (n_submitted only the admitted ones), so
        # chaos runs can assert conservation: every request is served,
        # shed, or failed — never lost, never hung.
        self.n_requests = 0
        self.n_submitted = 0
        self.n_served = 0
        self.n_shed = 0
        self.n_failed = 0
        self.n_degraded = 0   # requests served on a capped-iters rung
        self.n_continued = 0  # straggler re-bucket hops taken
        self.n_redispatched = 0  # engine-failover hand-offs
        self.n_folded = 0     # fresh rows folded into warm-group dispatches
        self.n_rejoined = 0   # engines re-admitted after probation
        self.n_affinity = 0   # requests routed by session affinity
        self.n_page_warm = 0  # rows warm-started from pool pages
        self.n_incremental = 0  # rows served on the incremental route
        # Per-SLO-class conservation counters (ISSUE 19: the aggregate
        # n_shed told one story for every tenant): lazily keyed by the
        # class names actually seen, each holding the same
        # served/shed/failed/degraded ledger, so conservation reconciles
        # PER TENANT (n_served + n_shed + n_failed == n_requests within
        # every class). Guarded by _counter_lock like its siblings.
        self._class_counts: dict = {}
        # Pad-tax rollup (ISSUE 11 satellite): per-dispatch pad_fraction
        # was stamped since PR 4 but never aggregated — the summary now
        # carries the mean plus the BYTES the padding wasted (pad token
        # positions x per-token column bytes), so `telemetry compare`
        # can gate pad-waste regressions. levels0 upload bytes aggregate
        # alongside (zero on the paged warm path — the acceptance
        # counter).
        self._pad_fraction_sum = 0.0
        self._pad_bytes_wasted = 0
        self._levels0_h2d_bytes = 0
        # Per-phase latency sums across dispatches (the summary's
        # latency_phases rollup — mean ms per phase per dispatch, what
        # `telemetry compare` gates as serve_latency.* costs).
        self._phase_sums: dict = {}
        # The most recent request's [c, H, W] shape — what the probation
        # health probe dispatches (engine-agnostic: the batcher never
        # assumes a model config). Guarded by _counter_lock: submit()
        # writes it, the probation thread reads it.
        self._probe_shape = None
        # FAIRNESS (ROADMAP item 1, observed while building rejoin-serve):
        # under slow paced traffic one worker can win EVERY 50ms-timeout
        # first-get race for seconds at a time — its loop re-enters get()
        # microseconds after a dispatch while the sibling's expired wait
        # re-queues behind it, and per-engine utilization phase-locks on
        # one engine. Two deterministic counters break the lock: the
        # worker that won the LAST first-get defers a small handicap when
        # the queue is idle (so an already-waiting sibling is first in
        # the queue's waiter list when the next request lands), and each
        # worker's first-get timeout carries a per-engine jitter so
        # equally-idle workers never expire in phase. _last_pickup rides
        # _counter_lock (worker threads write AND read it).
        self._last_pickup: Optional[str] = None
        self._pickup_handicap_s = 0.004
        self._engine_index = {
            self._ename(eng, i): i for i, eng in enumerate(self.engines)
        }
        self.dispatches: List[dict] = []  # one dict per dispatched batch
        # Per-request accounting, maintained INCREMENTALLY (a long-running
        # server must not retain one record per resolved request):
        # histogram of total executed iters, the same split by tier
        # (0 = resolved by the first dispatch, k = after k continuation
        # hops), and the running sum for the mean — the measurement units
        # of the two-tier win.
        self._iters_hist: dict = {}
        self._iters_hist_by_tier: dict = {}
        self._iters_total = 0
        self._counter_lock = threading.Lock()
        self._seq = 0
        # Elastic fleet state (serve/elastic.py). DRAINING engines stop
        # admitting but are NOT dead (their in-flight work flushes);
        # DRAINED engines have left the fleet voluntarily — kept in
        # `engines`/`_engine_state` as evidence husks (index math and the
        # summary's engines nest stay stable) but excluded from capacity
        # records, worker spawns, and the failover fleet-size accounting.
        # Both ride _engine_lock with the rest of the engine state.
        self._draining: set = set()
        self._drained: set = set()
        # Affinity items a draining worker handed back to the shared
        # queue on its way out (read by drain_engine's flush event).
        self._drain_handoff: dict = {}
        # Event taps (the autoscaler's in-process SLO monitor rides one):
        # each stamped serve record fans out to every tap after delivery.
        # A tap must never take down a worker — exceptions are swallowed.
        self._taps: List = []
        # The attached Autoscaler (None = static fleet, the default):
        # summary_record() nests its rollup under "elastic".
        self._elastic = None
        # Workload observatory (schema v9, serve/workload.py): armed by
        # enable_admission_events() at setup time. Off (the default) the
        # hot path pays one boolean read — no per-request events.
        self._admit_events = False
        # Drained-husk RETENTION (ROADMAP item 4 housekeeping): a
        # long-lived elastic server accumulates one evidence husk per
        # scale-in forever. When the lead ServeConfig bounds retention
        # (husk_max / husk_max_age_s; None = keep all, the pre-v9
        # shape), the oldest husks are RETIRED — removed from `engines`/
        # `_engine_state` entirely, their counters folded into the
        # _husks_retired rollup and stamped as an `engine_husk_retired`
        # event, so summary conservation still reconciles. The state
        # half (this bookkeeping) rides _engine_lock; the container
        # half follows add_engine's lock-free atomic-op convention
        # (see _prune_husks).
        self._husk_max = getattr(scfg, "husk_max", None) if scfg else None
        self._husk_max_age_s = (
            getattr(scfg, "husk_max_age_s", None) if scfg else None
        )
        self._husk_drained_at: dict = {}  # name -> batcher-clock drain time
        self._husks_retired: dict = {
            "n": 0, "dispatches": 0, "rejoins": 0, "age_s_max": 0.0,
        }

    @staticmethod
    def _ename(eng, i: int) -> str:
        return getattr(eng, "name", None) or f"engine{i}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DynamicBatcher":
        with self._counter_lock:
            started = bool(self._threads)
        if not started:
            self._stop.clear()
            for i, eng in enumerate(self.engines):
                name = self._ename(eng, i)
                with self._engine_lock:
                    if name in self._drained:
                        continue  # a drained husk never serves again
                t = threading.Thread(
                    target=self._worker,
                    args=(eng, name),
                    name=f"glom-serve-batcher-{name}",
                    daemon=True,
                )
                t.start()
                # _threads rides _counter_lock everywhere: the probation
                # path appends a revived engine's worker from ITS thread,
                # so the list is no longer caller-thread-only.
                with self._counter_lock:
                    self._threads.append(t)
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the workers. drain=True serves what is already queued
        first (the graceful path; stragglers resolve with their current
        state rather than opening new continuation hops); False fails
        queued requests FAST — both queues are drained and every ticket
        failed BEFORE waiting on the workers, so at most the in-flight
        batches dispatch after the call. Also safe on a never-started
        batcher: queued tickets are failed (drain=False) — there is no
        worker to ever resolve them. Probation threads (engine rejoin)
        observe the stop flag and exit on their next tick."""
        self._stop.set()
        if not drain:
            self._fail_queued()
        with self._counter_lock:
            threads = list(self._threads)
        for t in threads:
            # drain=True: a worker exits once the stop flag is set AND
            # both queues are empty — queued work is served on the way out.
            t.join(timeout=60.0)
        with self._counter_lock:
            self._threads = []
        # Whatever is STILL queued (drain=True with a dead/timed-out
        # worker, or a never-started batcher) can no longer resolve.
        self._fail_queued()

    def _fail_queued(self) -> None:
        while True:
            got = None
            try:
                got = [self._q.get_nowait()]
            except queue.Empty:
                try:
                    got = self._cont_q.get_nowait()  # a continuation group
                except queue.Empty:
                    for aq in list(self._aff_q.values()):
                        try:
                            got = [aq.get_nowait()]
                            break
                        except queue.Empty:
                            continue
                    if got is None:
                        return
            # Counted as FAILED: these tickets were admitted (n_submitted
            # incremented) and can no longer resolve — without the count,
            # summary_record()'s conservation (n_served + n_shed +
            # n_failed == n_requests) silently loses them.
            for item in got:
                with self._counter_lock:
                    self.n_failed += 1
                    self._bump_class_locked(item.ticket.slo_class, "n_failed")
                item.ticket._fail(ShedError("batcher stopped"))

    def __enter__(self) -> "DynamicBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission --------------------------------------------------------

    def _alive_engines(self) -> List[str]:
        """Engines that can take NEW work: alive and not draining (a
        draining engine still flushes its in-flight dispatch, but
        admission, affinity routing, and the ladder-shed vote must all
        stop seeing it)."""
        with self._engine_lock:
            return [
                n for n, st in self._engine_state.items()
                if st["alive"] and n not in self._draining
            ]

    def n_active_engines(self) -> int:
        """The live serving fleet size (alive, not draining) — the count
        the elastic policy clamps against."""
        return len(self._alive_engines())

    def engine_by_name(self, name: str):
        idx = self._engine_index.get(name)
        return self.engines[idx] if idx is not None else None

    def add_event_tap(self, tap) -> None:
        """Subscribe `tap(stamped_record)` to every record this batcher
        emits — the autoscaler's in-process SLO monitor reads the same
        stream `telemetry watch` would tail, with no file between.
        Registration is SETUP-time (before traffic): the list is
        append-only and the emit path reads a snapshot, so the hot path
        pays no lock for the common zero-tap case."""
        self._taps.append(tap)

    def enable_admission_events(self) -> None:
        """Arm per-request ADMISSION evidence (schema v9, the workload
        observatory — serve/workload.py): every submit() emits one
        compact "admit" event BEFORE the shed checks (a shed request was
        still OFFERED, and a replay must re-offer it), carrying arrival
        time, shape signature, and session; every ticket's terminal
        emits a "settle" event ("served" | "failed") via the ticket
        callback, so the recorder stitches outcomes without a hook at
        every failure site. Setup-time like add_event_tap; the un-armed
        hot path pays one boolean read."""
        self._admit_events = True

    def _signature(self, img, session_id) -> str:
        """The request's SHAPE CLASS — the unit the replay driver
        re-offers and the forecast buckets by: ragged admission priced
        per page ("ragged:<N>p"), delta streaming per session frame
        ("delta:CxHxW"), everything else by its image dims
        ("bucket:CxHxW"). Computed from np.shape WITHOUT converting the
        input (the admit event precedes the shed checks, which must not
        pay an asarray); malformed shapes fall through to the bucket
        form — submit's own validation raises the loud error."""
        shape = tuple(np.shape(img))
        dims = "x".join(str(int(d)) for d in shape)
        if self._ragged and len(shape) == 3:
            try:
                cfg = getattr(self.engine, "cfg", None)
                p = cfg.patch_size
                tokens = (shape[1] // p) * (shape[2] // p)
                pool = next(iter(self._pools.values()), None)
                if pool is not None:
                    pt = pool.page_tokens
                else:
                    from glom_tpu.serve.paged_columns import (
                        resolve_page_tokens,
                    )

                    pt = resolve_page_tokens(cfg, self.engine.scfg)
                pages = max(1, -(-tokens // pt))
                return f"ragged:{pages}p"
            except Exception:  # noqa: BLE001 — evidence, not validation
                return f"ragged:{dims}"
        scfg = getattr(self.engine, "scfg", None)
        if session_id is not None and getattr(
            scfg, "delta_streaming", False
        ):
            return f"delta:{dims}"
        return f"bucket:{dims}"

    def _settle_event(self, ticket: Ticket, outcome: str) -> None:
        """The per-request terminal leaf of the armed admission stream
        (Ticket._settled calls it exactly once, whichever path got
        there). Sheds keep their richer "shed" leaf; the recorder
        prefers it over the settle's "failed"."""
        self._emit(
            {
                "event": "settle",
                "request_id": ticket.request_id,
                "outcome": outcome,
                "latency_ms": (
                    round(1e3 * ticket._latency_s, 3)
                    if ticket._latency_s is not None else None
                ),
                "trace_id": ticket.trace_id,
                "slo_class": ticket.slo_class,
            }
        )

    def _bump_class_locked(self, slo_class, key: str, n: int = 1) -> None:
        """Advance one per-class conservation counter. Caller HOLDS
        _counter_lock (the sites all sit inside existing counter-lock
        blocks; taking it here would deadlock — threading.Lock is not
        reentrant). Unclassed requests (None) stay aggregate-only."""
        if slo_class is None:
            return
        c = self._class_counts.get(slo_class)
        if c is None:
            c = self._class_counts[slo_class] = {
                "n_requests": 0, "n_served": 0, "n_shed": 0,
                "n_failed": 0, "n_degraded": 0,
            }
        c[key] += n

    def attach_elastic(self, scaler) -> None:
        """Attach the Autoscaler whose rollup summary_record() nests
        under "elastic" (serve/elastic.py calls this; a static fleet
        never does, keeping the summary shape byte-for-byte)."""
        with self._counter_lock:
            self._elastic = scaler

    def submit(self, img, session_id=None, slo_class=None) -> Ticket:
        """Enqueue one [c, H, W] request. Sheds immediately (raises) when
        the queue is full, the backend is down, every engine is dead, or
        every live engine's degradation ladder is on its shed rung —
        admission never blocks the caller. Requests submitted before
        start() queue up and are served once the workers run; stop()
        fails whatever can no longer resolve, so a ticket is never
        silently stranded.

        `session_id` marks the request as one frame of a STREAM: at
        dispatch the worker warm-starts it from the session's cached
        column state when one is resident (serve/column_cache.py), and
        on resolve the converged columns are written back under the key
        for the stream's next frame. None (the default) is the
        stateless cold path, bit-for-bit the pre-streaming contract.

        `slo_class` names the request's SLO class (glom_tpu/serve/qos.py,
        docs/SERVING.md "SLO classes"): under a ServeConfig declaring
        slo_classes it routes admission through the class's bounded lane
        and the weighted-fair pick (None takes the default class; an
        UNDECLARED name raises ValueError before any counter moves). A
        classless config stamps the label on the request's records as
        pure observability — scheduling stays byte-for-byte FIFO."""
        if self._qos is not None:
            # Resolve BEFORE any counter or event: an unknown class is a
            # caller bug, not traffic — it must not dent conservation.
            slo_class = self._qos.resolve(slo_class)
        elif slo_class is not None:
            slo_class = str(slo_class)
        with self._counter_lock:
            self._seq += 1
            rid = self._seq
            self.n_requests += 1
            self._bump_class_locked(slo_class, "n_requests")
        # Mint the request's trace context HERE, at admission: trace_id
        # names the causal tree, span_id is the submit root every
        # first-hop record parents to (telemetry/tracectx.py). Tracing
        # off mints nothing — downstream records stamp the keys as null.
        if self._trace:
            ticket = Ticket(
                rid,
                trace_id=tracectx.new_trace_id(),
                span_id=tracectx.new_span_id(),
                slo_class=slo_class,
            )
        else:
            ticket = Ticket(rid, slo_class=slo_class)
        if self._admit_events:
            # The workload observatory's arrival record: emitted BEFORE
            # the shed checks — a shed request was offered traffic, and
            # the replay driver must re-offer it. np.shape reads lists
            # and arrays alike; conversion stays where it was.
            ticket._settle_cb = self._settle_event
            self._emit(
                {
                    "event": "admit",
                    "request_id": rid,
                    "t": round(self._clock(), 6),
                    "signature": self._signature(img, session_id),
                    "shape": [int(d) for d in np.shape(img)],
                    "session": session_id,
                    "trace_id": ticket.trace_id,
                    "slo_class": slo_class,
                }
            )
        with span("serve_enqueue", aggregator=self.spans):
            if self.shed_when_down and _backend_down():
                # trace_id rides the exception's detail too, so a caller
                # stamping its own failure record (the CLI's response)
                # can join it to the shed leaf without holding the ticket.
                detail = dict(self._pressure(), trace_id=ticket.trace_id)
                self._shed(ticket, "backend-down", **detail)
                raise BackendDownError(
                    "backend watchdog reports the accelerator down; "
                    "request shed (fast-fail, never a hang)",
                    **detail,
                )
            alive = self._alive_engines()
            with self._counter_lock:
                started = bool(self._threads)
            if started and not alive:
                detail = dict(self._pressure(), trace_id=ticket.trace_id)
                self._shed(ticket, "no-live-engine", **detail)
                raise ShedError(
                    "every engine is dead (failover exhausted); request "
                    "shed fast rather than stranded",
                    **detail,
                )
            live_ladders = [
                self._ladders[n] for n in (alive or list(self._ladders))
                if self._ladders.get(n) is not None
            ]
            if live_ladders:
                from glom_tpu.resilience.ladder import SHED

                # Class-aware shed gate (glom_tpu/serve/qos.py): the
                # first class in the shed order sheds a rung EARLY, the
                # premium end holds until the ladder's own floor — load
                # drops tenant-by-tenant. Classless keeps the SHED gate.
                shed_gate = SHED
                if self._qos is not None:
                    shed_gate = self._qos.shed_rung(slo_class)
                if min(l.rung() for l in live_ladders) >= shed_gate:
                    detail = dict(self._pressure(), trace_id=ticket.trace_id)
                    self._shed(ticket, "ladder-shed", **detail)
                    cls_note = (
                        f" for class {slo_class!r}"
                        if self._qos is not None else ""
                    )
                    raise LadderShedError(
                        f"degradation ladder at its shed rung{cls_note} "
                        "on every live engine (every cheaper serving "
                        "mode exhausted); retry later",
                        **detail,
                    )
            img = np.asarray(img, np.float32)
            n_patches = None
            if self._ragged:
                n_patches = self._ragged_patch_count(img)
            # SESSION AFFINITY (pages mode): a stream whose pages live
            # in a LIVE engine's pool routes to that engine's queue —
            # the warm path must reach the pool that holds the state.
            # Cold streams (and dead/unknown targets) ride the shared
            # queue's least-depth dispatch as always.
            target = None
            if (
                session_id is not None
                and self.cache is not None
                and self._pools
            ):
                t = self.cache.engine_of(session_id)
                if t is not None and t in alive and t in self._aff_q:
                    target = t
            # Count the admission BEFORE the put (rolled back on a full
            # queue): the instant the request is enqueued a worker may
            # serve it, and n_served must never exceed n_submitted even
            # transiently (the race harness caught both orderings that
            # counted after the put as off-by-ones).
            with self._counter_lock:
                self.n_submitted += 1
                self._probe_shape = img.shape
            item = _Item(img, ticket, session_id, n_patches=n_patches)
            item.t_enq = self._clock()
            placed = False
            if target is not None:
                try:
                    self._aff_q[target].put_nowait(item)
                    placed = True
                    with self._counter_lock:
                        self.n_affinity += 1
                except queue.Full:
                    pass  # fall back to the shared queue
                if placed:
                    # Race with a concurrent death OR drain: the failure
                    # handler sets alive=False (and drain_engine sets
                    # the draining flag) BEFORE draining the affinity
                    # queue, so either that drain saw this put, or we
                    # see the flag here and drain ourselves — the
                    # ticket can never strand in a queue no worker
                    # reads (a draining worker has already stopped
                    # reading its queue by the time the flag is set).
                    with self._engine_lock:
                        serving = (
                            self._engine_state[target]["alive"]
                            and target not in self._draining
                        )
                    if not serving:
                        self._drain_affinity(target)
            if not placed:
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    with self._counter_lock:
                        self.n_submitted -= 1
                    detail = dict(
                        self._pressure(), trace_id=ticket.trace_id
                    )
                    self._shed(ticket, "queue-full", **detail)
                    raise QueueFullError(
                        f"request queue at capacity ({self._q.maxsize}); "
                        "backpressure — retry later",
                        **detail,
                    ) from None
            with self._counter_lock:
                threads = list(self._threads)
            if self._stop.is_set() and not any(
                t.is_alive() for t in threads
            ):
                # Race with stop(): the put landed after the (dead or
                # never-started) workers' final drain — no one will ever
                # dispatch it, so fail it here rather than strand the
                # ticket. A LIVE draining worker still owns the queue.
                self._fail_queued()
                raise ShedError("batcher stopped")
        return ticket

    def _ragged_patch_count(self, img: np.ndarray) -> int:
        """Validate a ragged-mode request's shape and return its patch
        count: [c, H, W] with H and W multiples of the patch size and at
        most the full-resolution patch count (the pos table bounds a
        row's length)."""
        cfg = getattr(self.engine, "cfg", None)
        if cfg is None or img.ndim != 3:
            raise ValueError(
                f"ragged submit needs a [c, H, W] image; got {img.shape}"
            )
        p = cfg.patch_size
        c, h, w = img.shape
        if c != cfg.channels or h % p or w % p or h < p or w < p:
            raise ValueError(
                f"ragged image {img.shape}: channels must be "
                f"{cfg.channels} and H, W multiples of patch_size {p}"
            )
        n = (h // p) * (w // p)
        if n > cfg.num_patches:
            raise ValueError(
                f"{n} patches exceed the model's {cfg.num_patches} (the "
                "pos table bounds the row length)"
            )
        return n

    def _pressure(self, engine_name: Optional[str] = None) -> dict:
        """The machine-readable WHY of a shed/ladder decision: queue depth
        and capacity, plus the ladder rung when one is attached — these
        fields ride both the stamped record and the raised exception
        (before this, the shed path lost the why)."""
        detail = {
            "queue_depth": self._q.qsize(),
            "queue_capacity": self._q.maxsize,
            "continuations_queued": self._cont_q.qsize(),
        }
        if self._qos is not None:
            # Per-class lane pressure (glom_tpu/serve/qos.py): which
            # tenant's lane is actually full — the aggregate depth alone
            # reads one story for every class.
            detail["class_depth"] = {
                n: f["depth"] for n, f in self._q.class_fill().items()
            }
        ladder = self._ladders.get(
            engine_name or self._ename(self.engines[0], 0)
        )
        if ladder is not None:
            detail["rung"] = ladder.rung_name()
        return detail

    def _shed(self, ticket: Ticket, reason: str, **detail) -> None:
        with self._counter_lock:
            self.n_shed += 1
            self._bump_class_locked(ticket.slo_class, "n_shed")
        detail.setdefault("trace_id", ticket.trace_id)
        detail.setdefault("slo_class", ticket.slo_class)
        exc_type = {
            "backend-down": BackendDownError,
            "ladder-shed": LadderShedError,
            "no-live-engine": ShedError,
        }.get(reason, QueueFullError)
        ticket._fail(exc_type(reason, **detail))
        # The shed decision itself is a "serve" event carrying the why
        # (queue depth / ladder rung; stamp_serve merges backend_state)
        # plus the request's trace context — a shed is this trace's
        # terminal leaf, so `telemetry trace` shows WHY the request never
        # resolved. A backend-down shed ALSO emits the schema "error"
        # record (value null, machine-readable cause) — the same
        # UNMEASURED discipline as the benches.
        rec = {
            "event": "shed",
            "reason": reason,
            "request_id": ticket.request_id,
            "trace_id": ticket.trace_id,
            **detail,
        }
        if ticket.trace_id is not None:
            rec.setdefault("span_id", tracectx.new_span_id())
            rec.setdefault("parent_span", ticket.span_id)
        self._emit(rec)
        if reason == "backend-down":
            self._emit(
                {
                    "error": "backend-down",
                    "value": None,
                    "request_id": ticket.request_id,
                    "trace_id": ticket.trace_id,
                    "note": "request shed: backend watchdog reports down",
                },
                kind="error",
            )

    # -- the workers -------------------------------------------------------

    def _ladder_observe(self, engine_name: str) -> None:
        """Feed this engine's ladder one (pressure, backend) observation.
        Runs every worker cycle — INCLUDING idle ones, so a drained queue
        steps the ladder back up even when no traffic arrives to
        dispatch."""
        ladder = self._ladders.get(engine_name)
        if ladder is None:
            return
        from glom_tpu.telemetry.watchdog import backend_record

        fill = self._q.qsize() / max(1, self._q.maxsize)
        ladder.observe(
            queue_fill=fill,
            backend_state=backend_record().get("backend_state", "unknown"),
        )

    def _first_get_timeout(self, engine_name: str) -> float:
        """Per-engine jittered first-get timeout: 50ms base plus a
        deterministic per-engine offset (prime-stepped, bounded at +40%)
        so idle workers' timeout expiries drift apart instead of
        re-queueing in the same order forever."""
        idx = self._engine_index.get(engine_name, 0)
        return 0.05 * (1.0 + 0.4 * ((idx * 7) % 10) / 10.0)

    def _defer_pickup(self, engine_name: str) -> bool:
        """True when this worker should yield the next first-get: it won
        the last one, the queue is idle (the handicap must never slow a
        backed-up queue), and a live sibling exists to take the hand-off.
        Locks are taken SEQUENTIALLY in the documented engine->counter
        order (never nested here)."""
        if len(self.engines) < 2 or not self._q.empty():
            return False
        with self._engine_lock:
            has_sibling = any(
                st["alive"]
                for n, st in self._engine_state.items()
                if n != engine_name
            )
        if not has_sibling:
            return False
        with self._counter_lock:
            return self._last_pickup == engine_name

    def _gather(self, engine_name: str) -> List[_Item]:
        """Block for the first request, then gather until max_batch or the
        first request ages past max_delay — the two-knob admission. A
        ladder at bucket_cap or worse gathers smaller batches: smaller,
        faster dispatches drain a backed-up queue in bounded bites. The
        first get is fairness-rotated (see __init__): last winner defers
        a handicap on an idle queue, timeouts carry per-engine jitter."""
        max_batch = self._effective_max_batch(engine_name)
        aq = self._aff_q[engine_name]
        first = None
        try:
            # Affinity first: streams routed HERE hold pages in this
            # engine's pool — serving them elsewhere would cold-start.
            first = aq.get_nowait()
        except queue.Empty:
            pass
        if first is None:
            if self._defer_pickup(engine_name):
                time.sleep(self._pickup_handicap_s)
            try:
                first = self._q.get(
                    timeout=self._first_get_timeout(engine_name)
                )
            except queue.Empty:
                return []
            with self._counter_lock:
                self._last_pickup = engine_name
        batch = [first]
        deadline = self._clock() + self.max_delay_s
        while len(batch) < max_batch:
            try:
                batch.append(aq.get_nowait())
                continue
            except queue.Empty:
                pass
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _effective_max_batch(self, engine_name: str) -> int:
        """max_batch under the ladder's bucket cap (shared by _gather and
        the warm-group top-up, so both gathering paths degrade alike)."""
        max_batch = self.max_batch
        ladder = self._ladders.get(engine_name)
        if ladder is not None:
            from glom_tpu.resilience.ladder import BUCKET_CAP

            if ladder.rung() >= BUCKET_CAP:
                max_batch = min(max_batch, ladder.bucket_cap)
        return max_batch

    def _top_up(self, engine_name: str, have: int) -> List[_Item]:
        """MIXED warm/cold buckets: fold whatever fresh traffic is
        ALREADY waiting into a warm continuation group, up to the
        admission ceiling — a lone straggler no longer dispatches into a
        mostly-pad bucket, and the fresh rows it pulls in skip their own
        gathering delay. Non-blocking on purpose: stragglers are the
        oldest requests in the system, so the fold never ADDS latency
        waiting for company (an empty queue keeps the lone-group
        dispatch, the pre-fold contract)."""
        added: List[_Item] = []
        limit = self._effective_max_batch(engine_name)
        while have + len(added) < limit:
            try:
                added.append(self._q.get_nowait())
            except queue.Empty:
                break
        if added:
            with self._counter_lock:
                self.n_folded += len(added)
        return added

    def _worker(self, engine, engine_name: str) -> None:
        while not (
            self._stop.is_set()
            and self._q.empty()
            and self._cont_q.empty()
            and self._aff_q[engine_name].empty()
        ):
            with self._engine_lock:
                if not self._engine_state[engine_name]["alive"]:
                    break  # dead: queued work drains to siblings
                if engine_name in self._draining:
                    # Voluntary DRAIN (distinct from death — never into
                    # probation): the in-flight dispatch already
                    # completed (the flag is checked at loop top), so
                    # hand the affinity queue back to the shared queue
                    # and exit; stragglers this worker produced sit in
                    # the SHARED continuation queue for the siblings.
                    handed = self._drain_affinity(engine_name)
                    with self._counter_lock:
                        self._drain_handoff[engine_name] = (
                            self._drain_handoff.get(engine_name, 0) + handed
                        )
                    return
            self._ladder_observe(engine_name)
            # Continuations first: stragglers are the OLDEST requests in
            # the system; waiting fresh rows fold into their bucket's pad
            # slots (per-row levels0 select in _dispatch).
            try:
                group = self._cont_q.get_nowait()
            except queue.Empty:
                group = None
            if group is not None:
                batch = list(group)
                batch.extend(self._top_up(engine_name, len(batch)))
                self._dispatch(engine, engine_name, batch)
                continue
            with span("serve_batch", aggregator=self.spans):
                batch = self._gather(engine_name)
            if not batch:
                continue
            self._dispatch(engine, engine_name, batch)
        else:
            return  # normal stop-drain exit
        # Dead-engine exit: hand off to probation when rejoin is enabled
        # (N consecutive successful health dispatches re-admit the
        # engine); otherwise death stays terminal until restart. A
        # DRAINED/DRAINING engine never probes: a drain whose in-flight
        # flush outlived the join timeout reaches here with alive
        # already False — its devices are being released, and a rejoin
        # would re-admit a husk (the flag check below is the guard;
        # _start_probation re-checks under the lock).
        with self._engine_lock:
            voluntary = (
                engine_name in self._drained
                or engine_name in self._draining
            )
        if (
            self._rejoin_threshold > 0
            and not self._stop.is_set()
            and not voluntary
        ):
            self._start_probation(engine, engine_name)

    # -- engine rejoin (probation re-admit) --------------------------------

    def _start_probation(self, engine, engine_name: str) -> None:
        """Spawn the probation thread for a just-died engine (at most one
        per engine). The thread health-dispatches the smallest bucket
        until `rejoin_threshold` CONSECUTIVE successes re-admit the
        engine — a flapping engine that fails a probe starts its count
        over, so rejoin certifies sustained health, not one lucky call."""
        # Registration is ATOMIC with stop()'s thread snapshot (both ride
        # _counter_lock, nested in the documented engine->counter order):
        # either stop() already set the flag and nothing spawns, or the
        # thread lands in _threads before the snapshot and stop() joins
        # it — a probe thread can never outlive stop() untracked.
        with self._engine_lock:
            st = self._engine_state[engine_name]
            if st["alive"] or st["probation"]:
                return
            if (
                engine_name in self._drained
                or engine_name in self._draining
            ):
                return  # voluntary exit: released husks never probe back
            with self._counter_lock:
                if self._stop.is_set():
                    return
                st["probation"] = True
                t = threading.Thread(
                    target=self._probation,
                    args=(engine, engine_name),
                    name=f"glom-serve-probation-{engine_name}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
        self._emit(
            {
                "event": "engine_probation",
                "engine": engine_name,
                "need": self._rejoin_threshold,
            }
        )

    def _probation(self, engine, engine_name: str) -> None:
        ok = 0
        while not self._stop.wait(self._rejoin_interval_s):
            with self._counter_lock:
                shape = self._probe_shape
            cfg = getattr(engine, "cfg", None)
            if cfg is not None:
                # A config-carrying engine probes at its own full
                # resolution — ragged traffic's last-seen shape may be a
                # smaller canvas than the bucket signatures compile for.
                shape = (cfg.channels, cfg.image_size, cfg.image_size)
            if shape is None:
                continue  # no traffic seen yet: nothing to probe with
            try:
                bucket = engine.pick_bucket(1)
                engine.infer(np.zeros((bucket, *shape), np.float32), n_valid=1)
                ok += 1
            except BaseException:  # noqa: BLE001 — a failed probe is data
                ok = 0
                continue
            if ok < self._rejoin_threshold:
                continue
            # Re-admit: alive again with a clean failure count, its cache
            # entries long invalidated (death dropped them) — the engine
            # re-earns warm state from fresh write-backs. The stop-check,
            # the alive flip, and the worker's start+registration are ONE
            # critical section shared with stop()'s snapshot (engine ->
            # counter lock order): a stop() that already snapshotted
            # cannot miss the new worker, and a stop() that already set
            # the flag gets no worker at all — no duplicate or orphan
            # worker can survive a stop()/rejoin race (review-caught).
            with self._engine_lock:
                with self._counter_lock:
                    if self._stop.is_set():
                        self._engine_state[engine_name]["probation"] = False
                        return
                    st = self._engine_state[engine_name]
                    st["alive"] = True
                    st["consecutive_failures"] = 0
                    st["probation"] = False
                    st["rejoins"] += 1
                    self.n_rejoined += 1
                    worker = threading.Thread(
                        target=self._worker,
                        args=(engine, engine_name),
                        name=f"glom-serve-batcher-{engine_name}",
                        daemon=True,
                    )
                    # Started INSIDE the critical section: its first loop
                    # step blocks on _engine_lock until we release, and a
                    # joiner can never see a registered-but-unstarted
                    # thread.
                    worker.start()
                    self._threads.append(worker)
            self._emit(
                {
                    "event": "engine_rejoin",
                    "engine": engine_name,
                    "health_dispatches": ok,
                }
            )
            return
        # Stopped while still on probation: leave the engine dead.
        with self._engine_lock:
            self._engine_state[engine_name]["probation"] = False

    # -- elastic fleet (serve/elastic.py) ----------------------------------

    def add_engine(
        self,
        engine,
        *,
        name: Optional[str] = None,
        detail: Optional[dict] = None,
    ) -> str:
        """Register a NEW engine replica at runtime — the autoscaler's
        scale-out landing. The engine must arrive FULLY WARMED: admission
        opens the instant its worker starts (the scaler runs warmup()
        before calling this — test-pinned: a spawned engine receives zero
        admitted work before its precompile completes). Registration
        mirrors __init__ per-engine setup: ladder (resolved from the
        engine's own ServeConfig), affinity queue, engine state, page
        pool (pages-mode fleets stay homogeneous — loudly). `detail`
        merges into the stamped engine_add event (the autoscaler threads
        the owning decision_id/fleet through it, so the audit CLI can
        chain the registration to its decision). Returns the engine's
        fleet name."""
        ename = name or getattr(engine, "name", None)
        pool = getattr(engine, "pool", None)
        pages_mode = (
            self.cache is not None
            and getattr(self.cache, "pools", None) is not None
        )
        if pages_mode and pool is None:
            raise ValueError(
                "pages-mode fleet: a runtime-added engine must carry a "
                "page pool (mixed pool/pool-less fleets are unsupported)"
            )
        # Resolve the engine's ladder OUTSIDE the locks (pure config).
        ladder = None
        escfg = getattr(engine, "scfg", None)
        if (
            escfg is not None
            and getattr(escfg, "ladder", False)
            and getattr(engine, "cfg", None) is not None
        ):
            from glom_tpu.resilience.ladder import DegradationLadder

            ladder = DegradationLadder.from_config(
                engine.cfg, escfg, writer=self.writer
            )
        # Phase 1 — RESERVE the name: the state entry exists (duplicate
        # registration is impossible from here) but reads alive=False +
        # probation=True, so admission, affinity routing, drain, and the
        # capacity stream (state "probation" — excluded from the
        # headroom min) all ignore the half-registered engine.
        with self._engine_lock:
            if ename is None:
                k = len(self._engine_state)
                while f"engine{k}" in self._engine_state:
                    k += 1
                ename = f"engine{k}"
            elif ename in self._engine_state:
                raise ValueError(
                    f"engine name {ename!r} already registered"
                )
            self._engine_state[ename] = {
                "alive": False,
                "dispatches": 0,
                "consecutive_failures": 0,
                "probation": True,
                "rejoins": 0,
            }
        # Phase 2 — container registration. Each is one atomic setitem/
        # append on an otherwise construction-time container (the
        # codebase's convention for these: no reader holds a lock), and
        # nothing routes to the engine until phase 3 flips it alive.
        self.engines.append(engine)
        self._engine_index[ename] = len(self.engines) - 1
        self._aff_q[ename] = queue.Queue(maxsize=self._q.maxsize)
        self._ladders[ename] = ladder
        if pool is not None:
            self._pools[ename] = pool
        if pages_mode and pool is not None:
            self.cache.add_pool(ename, pool)
        # Phase 3 — open admission, atomically with stop()'s thread
        # snapshot (the probation-spawn pattern): a stopped batcher
        # keeps the engine registered but spawns no worker.
        with self._engine_lock:
            st = self._engine_state[ename]
            with self._counter_lock:
                st["alive"] = True
                st["probation"] = False
                if bool(self._threads) and not self._stop.is_set():
                    t = threading.Thread(
                        target=self._worker,
                        args=(engine, ename),
                        name=f"glom-serve-batcher-{ename}",
                        daemon=True,
                    )
                    t.start()
                    self._threads.append(t)
        self._emit(
            {
                "event": "engine_add",
                "engine": ename,
                "n_engines": self.n_active_engines(),
                **(detail or {}),
            }
        )
        return ename

    def begin_drain(self, name: str, *, detail: Optional[dict] = None) -> None:
        """Enter the DRAINING state: the engine stops admitting (it
        leaves _alive_engines — affinity routing, the ladder-shed vote,
        and failover sibling lists all stop seeing it) while its worker
        finishes the in-flight dispatch and exits. Refuses loudly when
        the engine is dead, on probation, already draining, or the LAST
        live engine (a fleet must never drain itself to zero)."""
        with self._engine_lock:
            st = self._engine_state.get(name)
            if st is None:
                raise ValueError(f"unknown engine {name!r}")
            if name in self._drained or name in self._draining:
                raise ValueError(f"engine {name} is already drained/draining")
            if not st["alive"] or st["probation"]:
                raise ValueError(
                    f"engine {name} is not drainable (dead or on "
                    "probation — drain is a voluntary transition of a "
                    "HEALTHY engine)"
                )
            others = [
                n for n, s in self._engine_state.items()
                if n != name and s["alive"] and n not in self._draining
            ]
            if not others:
                raise ValueError(
                    f"refusing to drain {name}: it is the last live "
                    "engine (min fleet is 1)"
                )
            self._draining.add(name)
        self._emit(
            {"event": "drain_begin", "engine": name, **(detail or {})}
        )

    def _join_worker(self, name: str, timeout: float) -> bool:
        """Wait for `name`'s worker thread to exit (the in-flight
        flush). True when it is gone inside the timeout."""
        tname = f"glom-serve-batcher-{name}"
        deadline = time.monotonic() + timeout
        while True:
            with self._counter_lock:
                workers = [
                    t for t in self._threads
                    if t.name == tname and t.is_alive()
                ]
            if not workers:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            workers[0].join(timeout=min(0.5, remaining))

    def _migration_target(self, src: str) -> Optional[str]:
        """Where a draining engine's cache sessions land: the live
        non-draining sibling — in pages mode, the one whose pool has the
        most free pages (best chance every session fits)."""
        with self._engine_lock:
            live = [
                n for n, s in self._engine_state.items()
                if n != src and s["alive"] and n not in self._draining
            ]
        if self.cache is not None and getattr(self.cache, "pools", None):
            pooled = [
                (self._pools[n].n_pages - self._pools[n].pages_used(), n)
                for n in live
                if n in self._pools
            ]
            return max(pooled)[1] if pooled else None
        return live[0] if live else None

    def drain_engine(
        self,
        name: str,
        *,
        timeout: float = 60.0,
        detail: Optional[dict] = None,
    ) -> dict:
        """The graceful scale-in state machine (ROADMAP item 1; the
        autoscaler's actuator, also callable directly):

          1. begin_drain — stop admitting (stamped drain_begin);
          2. FLUSH — the worker finishes its in-flight dispatch, hands
             its affinity queue back to the shared queue, and exits;
             stragglers it produced sit in the SHARED continuation queue
             for the siblings (stamped drain_flush);
          3. MIGRATE — every cache session whose state lives on this
             engine moves to a sibling pool (bitwise — a byte round
             trip), falling back to a stamped `drain` invalidation when
             no sibling has page budget (stamped drain_migrate);
          4. the engine leaves the fleet as DRAINED — distinct from dead
             (no probation, no failover accounting, no capacity record).

        Device release (engine.release()) is the CALLER's step — the
        autoscaler stamps drain_release around it. Returns the drain
        stats. `detail` (e.g. the decision_id) merges into every stamped
        event so the evidence chain joins."""
        detail = dict(detail or {})
        self.begin_drain(name, detail=detail)
        t0 = time.monotonic()
        flushed = self._join_worker(name, timeout)
        # Belt-and-braces: a never-started batcher has no worker to hand
        # the affinity queue back — drain it here either way.
        handed = self._drain_affinity(name)
        with self._counter_lock:
            handed += self._drain_handoff.pop(name, 0)
        self._emit(
            {
                "event": "drain_flush",
                "engine": name,
                "flush_ok": flushed,
                "n_affinity_handed_back": handed,
                "continuations_queued": self._cont_q.qsize(),
                "flush_ms": round(1e3 * (time.monotonic() - t0), 3),
                **detail,
            }
        )
        stats = {
            "engine": name,
            "flush_ok": flushed,
            "n_migrated": 0,
            "n_invalidated": 0,
            "bytes_migrated": 0,
        }
        dst = None
        if self.cache is not None:
            dst = self._migration_target(name)
            mig = self.cache.migrate_engine_sessions(
                name, dst, reason="drain"
            )
            stats.update(mig)
        # Emitted even with no cache (zero counts): the drain chain the
        # chaos run reconstructs is always complete.
        self._emit(
            {
                "event": "drain_migrate",
                "engine": name,
                "dst_engine": dst,
                "n_migrated": stats["n_migrated"],
                "n_invalidated": stats["n_invalidated"],
                "bytes_migrated": stats["bytes_migrated"],
                **detail,
            }
        )
        # Namedness is decided here, outside the lock (engine_by_name's
        # convention): only NAMED husks enter _husk_drained_at and are
        # ever retirement candidates — removing an unnamed engine (test
        # fakes keyed by list index) would renumber its siblings'
        # evidence.
        eng = self.engine_by_name(name)
        named = getattr(eng, "name", None) is not None
        with self._engine_lock:
            st = self._engine_state[name]
            st["alive"] = False
            self._draining.discard(name)
            self._drained.add(name)
            if named:
                self._husk_drained_at[name] = self._clock()
        # The drained pool leaves the fleet maps (its record would
        # otherwise ride every later summary as live capacity).
        self._pools.pop(name, None)
        if self.cache is not None:
            self.cache.remove_pool(name)
        self._prune_husks()
        return stats

    def _prune_husks(self) -> None:
        """Drained-husk RETENTION (schema v9): bound the evidence husks a
        long-lived elastic server keeps. With husk_max / husk_max_age_s
        unset (the default) this is a no-op and every husk is retained —
        the pre-v9 shape. Otherwise the oldest husks past either bound
        are RETIRED: removed from `engines`/`_engine_state`/`_drained`,
        their counters folded into the _husks_retired rollup
        (summary_record nests it, so per-engine dispatch totals still
        reconcile against the globals), and one `engine_husk_retired`
        event stamped per retirement. Unnamed engines (test fakes keyed
        by list index) are never retired — removing one would renumber
        its siblings' evidence."""
        if self._husk_max is None and self._husk_max_age_s is None:
            return
        now = self._clock()
        retired = []  # (name, age_s, reason, dispatches, rejoins)
        # Phase 1 — select victims and retire their STATE under the
        # lock. Popping _engine_state is the commit point: concurrent
        # prunes race to it and the loser skips, so each husk retires
        # exactly once and the conservation fold is exact. With the
        # name out of _engine_state/_drained nothing routes to, drains,
        # or reports the husk any more. Only NAMED husks ever enter
        # _husk_drained_at (the drain site decides), so no unnamed
        # engine is ever selected here.
        with self._engine_lock:
            husks = sorted(
                (n for n in self._drained if n in self._husk_drained_at),
                key=lambda n: self._husk_drained_at[n],
            )
            marked = {}
            if self._husk_max_age_s is not None:
                for n in husks:
                    age = now - self._husk_drained_at[n]
                    if age > self._husk_max_age_s:
                        marked[n] = "age-bound"
            if self._husk_max is not None:
                kept = [n for n in husks if n not in marked]
                for n in kept[: max(0, len(kept) - self._husk_max)]:
                    marked[n] = "count-bound"
            for n in husks:
                if n not in marked:
                    continue
                st = self._engine_state.pop(n, None)
                if st is None:
                    continue  # a concurrent prune won the commit
                self._drained.discard(n)
                age = now - self._husk_drained_at.pop(n)
                self._drain_handoff.pop(n, None)
                fold = self._husks_retired
                fold["n"] += 1
                fold["dispatches"] += st.get("dispatches", 0)
                fold["rejoins"] += st.get("rejoins", 0)
                fold["age_s_max"] = round(max(fold["age_s_max"], age), 3)
                retired.append(
                    (n, age, marked[n], st.get("dispatches", 0),
                     st.get("rejoins", 0))
                )
        # Phase 2 — container teardown OUTSIDE the lock, mirroring
        # add_engine's registration convention: `engines`/
        # `_engine_index`/`_aff_q`/`_ladders` are the lock-free
        # containers no reader guards, so they are trimmed with single
        # atomic ops only. The husk serves nothing (phase 1 already
        # unregistered it), so the brief window where the list and the
        # index disagree is visible only to fleet observers, never to a
        # dispatch.
        for name, age, reason, dispatches, rejoins in retired:
            self._ladders.pop(name, None)
            self._aff_q.pop(name, None)
            idx = self._engine_index.get(name)
            if idx is not None:
                del self.engines[idx]
                self._engine_index = {
                    self._ename(eng, i): i
                    for i, eng in enumerate(self.engines)
                }
            self._emit(
                {
                    "event": "engine_husk_retired",
                    "engine": name,
                    "reason": reason,
                    "age_s": round(age, 3),
                    "dispatches": dispatches,
                    "rejoins": rejoins,
                }
            )

    # -- dispatch ----------------------------------------------------------

    @staticmethod
    def _token_state_bytes(engine) -> Optional[int]:
        """Per-token column bytes (L x d x itemsize) — the pad-waste
        pricing unit. None for config-less fake engines."""
        cfg = getattr(engine, "cfg", None)
        scfg = getattr(engine, "scfg", None)
        if cfg is None or scfg is None:
            return None
        itemsize = (
            2 if getattr(scfg, "compute_dtype", "") == "bfloat16" else 4
        )
        return cfg.levels * cfg.dim * itemsize

    def _degrade_gate(self, batch) -> int:
        """The ladder rung at which THIS batch's route degrades: the
        most protected class present wins (qos.py class_rungs) — one
        premium row holds the whole dispatch at its full route until
        the ladder reaches premium's own degrade rung, while a
        pure-batch dispatch degrades at the classless rung. Classless
        configs: capped_iters, the pre-QoS semantics unchanged."""
        from glom_tpu.resilience.ladder import CAPPED_ITERS

        if self._qos is None:
            return CAPPED_ITERS
        return max(self._qos.degrade_rung(it.slo_class) for it in batch)

    @staticmethod
    def _class_rows(items) -> Optional[dict]:
        """{slo_class: n_rows} over classed items — None when nothing
        is classed, so classless records stay byte-identical."""
        rows: dict = {}
        for it in items:
            if it.slo_class is not None:
                rows[it.slo_class] = rows.get(it.slo_class, 0) + 1
        return rows or None

    def _note_dispatch(self, engine_name: str, rec: dict, resolved: List[dict],
                       n_served: int, n_degraded: int, n_continued: int,
                       class_served: Optional[dict] = None) -> None:
        """Per-engine + global bookkeeping for one successful dispatch,
        under BOTH locks in the documented order — the per-engine
        dispatch count and the conservation counters must be mutually
        consistent for summary_record()'s snapshot."""
        with self._engine_lock:  # LOCK ORDER: _engine_lock -> _counter_lock
            st = self._engine_state[engine_name]
            st["dispatches"] += 1
            st["consecutive_failures"] = 0
            with self._counter_lock:
                self.n_served += n_served
                self.n_degraded += n_degraded
                if class_served:
                    # A degraded dispatch degrades EVERY row it resolves,
                    # so per-class degraded rides the same row counts.
                    for cls, k in class_served.items():
                        self._bump_class_locked(cls, "n_served", k)
                        if n_degraded:
                            self._bump_class_locked(cls, "n_degraded", k)
                self.n_continued += n_continued
                self.n_page_warm += rec.get("n_page_warm") or 0
                self.n_incremental += rec.get("n_incremental") or 0
                self._pad_fraction_sum += rec.get("pad_fraction") or 0.0
                self._pad_bytes_wasted += rec.get("pad_bytes") or 0
                self._levels0_h2d_bytes += (
                    rec.get("levels0_h2d_bytes") or 0
                )
                from glom_tpu.telemetry.tracectx import PHASE_KEYS

                for k in PHASE_KEYS:
                    v = rec.get(k)
                    if isinstance(v, (int, float)):
                        self._phase_sums[k] = (
                            self._phase_sums.get(k, 0.0) + v
                        )
                self.dispatches.append(rec)
                for r in resolved:
                    key = str(r["iters"])
                    self._iters_hist[key] = self._iters_hist.get(key, 0) + 1
                    tier = self._iters_hist_by_tier.setdefault(
                        str(r["tier"]), {}
                    )
                    tier[key] = tier.get(key, 0) + 1
                    self._iters_total += r["iters"]

    def _note_failure(self, engine_name: str) -> dict:
        """One dispatch failure's engine-state transition; returns a
        snapshot {alive, siblings} the failover decision reads."""
        with self._engine_lock:  # LOCK ORDER: _engine_lock -> _counter_lock
            st = self._engine_state[engine_name]
            st["consecutive_failures"] += 1
            # The single-engine fleet never marks itself dead (it keeps
            # serving/retrying) — DRAINED husks and DRAINING engines
            # don't count toward the fleet size: while a sibling drains,
            # the one remaining admitting engine IS the single-engine
            # fleet and must keep that contract rather than kill all
            # admission. (_engine_state mirrors the engines list
            # one-to-one — the lock-clean fleet count.)
            fleet = (
                len(self._engine_state)
                - len(self._drained)
                - len(self._draining)
            )
            if (
                st["consecutive_failures"] >= self.engine_fail_threshold
                and fleet > 1
            ):
                st["alive"] = False
            siblings = [
                n
                for n, s in self._engine_state.items()
                if n != engine_name and s["alive"]
                and n not in self._draining
            ]
            return {"alive": st["alive"], "siblings": siblings}

    def _requeue(self, items) -> int:
        """Hand a failed dispatch's requests to the sibling engines via
        the shared queues; tickets whose redispatch budget is exhausted
        fail instead (bounded — a poison batch cannot ping-pong forever).
        Mixed batches split per row: continuation stragglers keep their
        mid-flight warm state (it is THEIR computed progress) and rejoin
        the continuation queue as one group; cache-warmed rows DROP their
        warmth back to cold — the failing engine's cache entries are
        being invalidated right now, and a re-dispatch must re-decide
        against the post-invalidation cache, never ride state read before
        the failure. Returns how many were requeued."""
        requeued = 0
        warm_survivors: List[_Item] = []
        for item in items:
            item.redispatches += 1
            item.t_enq = self._clock()  # next hop's queue_wait starts now
            if item.redispatches > self.max_redispatch:
                with self._counter_lock:
                    self.n_failed += 1
                    self._bump_class_locked(item.ticket.slo_class, "n_failed")
                item.ticket._fail(
                    ShedError(
                        "redispatch budget exhausted "
                        f"({self.max_redispatch}) after engine failures"
                    )
                )
                continue
            if item.warm_src in ("cache", "pages"):
                # Cache/page warmth drops to COLD on requeue: the
                # failing engine's entries (and pool pages) are being
                # invalidated right now — a re-dispatch must re-decide
                # against the post-invalidation cache.
                item.levels = None
                item.pages = None
                item.warm_src = None
            if item.levels is not None:
                warm_survivors.append(item)
                continue
            try:
                self._q.put_nowait(item)
                requeued += 1
            except queue.Full:
                with self._counter_lock:
                    self.n_failed += 1
                    self._bump_class_locked(item.ticket.slo_class, "n_failed")
                item.ticket._fail(
                    QueueFullError("requeue after engine failure: full")
                )
        if warm_survivors:
            self._cont_q.put(warm_survivors)
            requeued += len(warm_survivors)
        with self._counter_lock:
            self.n_redispatched += requeued
        return requeued

    def _drain_affinity(self, engine_name: str) -> int:
        """A dead (or draining) engine's affinity queue drains back to
        the SHARED queue (its streams serve on a sibling — cold after a
        death, still warm after a drain-migration). Tickets that no
        longer fit anywhere fail fast. Returns how many moved."""
        aq = self._aff_q.get(engine_name)
        if aq is None:
            return 0
        moved = 0
        while True:
            try:
                item = aq.get_nowait()
            except queue.Empty:
                return moved
            try:
                self._q.put_nowait(item)
                moved += 1
            except queue.Full:
                with self._counter_lock:
                    self.n_failed += 1
                    self._bump_class_locked(item.ticket.slo_class, "n_failed")
                item.ticket._fail(
                    QueueFullError("affinity drain after engine death: full")
                )

    def _ragged_chunks(self, engine, batch) -> List[list]:
        """Split a gathered ragged batch so each chunk's total pages fit
        the largest ragged signature (one chunk in the common case — the
        default ladder tops at max_batch x pages-per-full-row).

        Rung selection is PAD-AWARE, not token round-up only: packing
        one more row can escalate the chunk onto the next ladder rung,
        and on a fine ladder the escalation's round-up pad can exceed
        the pad of closing the chunk where it is and starting the row
        fresh — the one-token-overflow-doubles-the-band shape. Compare
        both pads in pages and close early only when it strictly wins
        (ties pack, preserving the coarse-ladder behavior where
        escalation is always at least as tight)."""
        from glom_tpu.serve.paged_columns import (
            pages_for_tokens,
            resolve_page_tokens,
        )

        pool = getattr(engine, "pool", None)
        pt = (
            pool.page_tokens if pool is not None
            else resolve_page_tokens(engine.cfg, engine.scfg)
        )
        top = max(engine.ragged_page_buckets)
        chunks: List[list] = []
        cur: List = []
        pages = 0
        for it in batch:
            need = pages_for_tokens(it.n_patches, pt)
            close = False
            if cur:
                if pages + need > top:
                    close = True
                else:
                    rung_grow = engine.pick_pages(pages + need)
                    rung_cur = engine.pick_pages(pages)
                    if rung_grow > rung_cur:
                        # Escalating pad vs close-here pad: the current
                        # chunk's round-up plus the row opening its own
                        # chunk at its own rung.
                        pad_grow = rung_grow - (pages + need)
                        pad_close = (rung_cur - pages) + (
                            engine.pick_pages(need) - need
                        )
                        close = pad_grow > pad_close
            if close:
                chunks.append(cur)
                cur, pages = [], 0
            cur.append(it)
            pages += need
        if cur:
            chunks.append(cur)
        return chunks

    def _dispatch(self, engine, engine_name: str, batch) -> None:
        if self.shed_when_down and _backend_down():
            # Gathered but undispatchable: fail every ticket fast with the
            # stamped evidence — never dispatch into a dead backend (the
            # round-5 hang this subsystem exists to never reproduce).
            for req in batch:
                self._shed(
                    req.ticket, "backend-down", **self._pressure(engine_name)
                )
            return
        if self._ragged and hasattr(engine, "infer_ragged"):
            for chunk in self._ragged_chunks(engine, batch):
                self._dispatch_traced(engine, engine_name, chunk)
            return
        self._dispatch_traced(engine, engine_name, batch)

    def _dispatch_traced(self, engine, engine_name: str, batch) -> None:
        if self._trace:
            # One span per dispatch ATTEMPT: the batch-level records of
            # this dispatch (dispatch/continuation/failover) share it, and
            # the thread-local scope hands it to every nested sink (retry
            # recovery events, cache evictions, lazy warmup compiles, host
            # spans) without signature threading. parent_spans is row-
            # aligned with the batch: each row parents to ITS previous hop
            # (the submit root on the first).
            dspan = tracectx.new_span_id()
            tfields = {
                "span_id": dspan,
                "trace_ids": [it.ticket.trace_id for it in batch],
                "parent_spans": [it.parent_span for it in batch],
            }
            with tracectx.dispatch_scope(
                dspan, tfields["trace_ids"], tfields["parent_spans"]
            ):
                self._dispatch_one(engine, engine_name, batch, dspan, tfields)
        else:
            # Untraced: the context keys still stamp — as null, so the
            # schema's presence contract holds (an explicitly untraced
            # record lints; an absent key would not).
            self._dispatch_one(
                engine, engine_name, batch, None, {"trace_ids": None}
            )

    def _phase_fields(self, queue_wait_s, pack_s, result, fetch_s):
        """(phase dict, latency_ms) for one dispatch record — THE
        latency_ms definition under phase_split: the five phases (rounded
        to 3 decimals each) summed left to right in tracectx.PHASE_KEYS
        order, so `telemetry trace`'s extended conservation check can
        recompute the exact float sum. queue_wait/pack are batcher wall;
        h2d and the engine-side resolve come from the engine's own split;
        device is the engine dispatch wall MINUS both (absorbing what the
        split cannot see — validation, retry backoff — so the phases
        always partition the whole); the batcher's host fetch of the
        result rides resolve. Split off: keys stamp null (presence, like
        the trace-context contract) and latency_ms is the bare engine
        wall — the pre-v7 reading."""
        from glom_tpu.telemetry.tracectx import PHASE_KEYS

        if not self._phase_split:
            return (
                {k: None for k in PHASE_KEYS},
                round(1e3 * result.latency_s, 3),
            )
        eng_ms = 1e3 * result.latency_s
        eph = getattr(result, "phases", None) or {}
        h2d = float(eph.get("h2d_ms") or 0.0)
        eng_resolve = float(eph.get("resolve_ms") or 0.0)
        device = max(0.0, eng_ms - h2d - eng_resolve)
        phases = {
            "queue_wait_ms": round(max(0.0, 1e3 * queue_wait_s), 3),
            "pack_ms": round(max(0.0, 1e3 * pack_s), 3),
            "h2d_ms": round(h2d, 3),
            "device_ms": round(device, 3),
            "resolve_ms": round(eng_resolve + max(0.0, 1e3 * fetch_s), 3),
        }
        latency_ms = 0.0
        for k in PHASE_KEYS:
            latency_ms = latency_ms + phases[k]
        return phases, latency_ms

    def _note_item_phases(self, item, phases) -> None:
        """Accumulate one hop's rounded phase values onto the item — the
        resolve leaf's phase_ms_total, added in hop order so the
        conservation sum is bit-exact."""
        if not self._phase_split:
            return
        for k, v in phases.items():
            if isinstance(v, (int, float)):
                item.phase_ms[k] = item.phase_ms.get(k, 0.0) + v

    def _dispatch_one(
        self, engine, engine_name: str, batch, dspan, tfields
    ) -> None:
        if self._ragged and hasattr(engine, "infer_ragged"):
            self._dispatch_ragged_batch(
                engine, engine_name, batch, dspan, tfields
            )
        else:
            self._dispatch_batch(engine, engine_name, batch, dspan, tfields)

    def _handle_dispatch_failure(
        self, engine_name: str, batch, e, dspan, tfields, n: int, warm: bool
    ) -> None:
        """One dispatch failure's full consequence chain, shared by the
        bucket and ragged routes: engine-state transition, cache (and
        page) invalidation BEFORE any requeue, affinity fallback,
        sibling failover or per-ticket failure, and the stamped
        evidence."""
        state = self._note_failure(engine_name)
        if self.cache is not None:
            # A failing engine's cache entries are suspect the moment
            # the failure is observed: drop them BEFORE any requeue
            # re-decides warmth, so stale or dead-engine state can
            # never warm-start a request (docs/SERVING.md). In pages
            # mode this FREES the engine's pool pages (the cache's
            # _drop returns them) — exactly as cache invalidation
            # does, before any failover requeue.
            self.cache.invalidate_engine(engine_name)
        if not state["alive"]:
            # Streams routed here by session affinity fall back to
            # the shared queue (their pages just died with the pool).
            self._drain_affinity(engine_name)
        if state["siblings"]:
            # FAILOVER: hand this batch to the siblings instead of
            # failing it — the multi-engine contract a dead engine's
            # chaos scenario validates (docs/RESILIENCE.md). The
            # failover record takes this attempt's span (the failed
            # dispatch emitted no record of its own), and the items
            # re-parent to it, so the redispatch hop is a CHILD of
            # the failover in each request's causal tree.
            if dspan is not None:
                for item in batch:
                    item.parent_span = dspan
            n_req = self._requeue(batch)
            self._emit(
                {
                    "event": "engine_failover",
                    "engine": engine_name,
                    "engine_alive": state["alive"],
                    "n_requeued": n_req,
                    "n_valid": n,
                    "warm_state": warm,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                    **tfields,
                }
            )
            if not state["alive"]:
                self._emit(
                    {"event": "engine_dead", "engine": engine_name}
                )
            if not self._alive_engines():
                # The sibling snapshot raced a concurrent death: the
                # requeued batch landed in queues no live worker will
                # drain — fail it (and everything else queued) now
                # rather than strand tickets until stop().
                self._fail_queued()
            return
        with self._counter_lock:
            self.n_failed += len(batch)
            for req in batch:
                self._bump_class_locked(req.ticket.slo_class, "n_failed")
        for req in batch:
            req.ticket._fail(e)
        self._emit(
            {
                "event": "dispatch_error",
                "engine": engine_name,
                "n_valid": n,
                "exception": f"{type(e).__name__}: {e}"[:300],
                **tfields,
            }
        )
        if not state["alive"]:
            self._emit({"event": "engine_dead", "engine": engine_name})
            if not self._alive_engines():
                # The LAST engine just died: nothing will ever drain
                # the queues — fail what is waiting rather than
                # strand it until stop() (tickets stay terminal).
                self._fail_queued()

    def _dispatch_batch(
        self, engine, engine_name: str, batch, dspan, tfields
    ) -> None:
        # Phase anchors: queue_wait ends (and pack begins) the moment the
        # worker starts processing this batch; the oldest item's enqueue
        # time anchors the wait (the same "oldest request" convention the
        # max_delay admission knob uses).
        t_proc = self._clock()
        queue_wait_s = t_proc - min(
            (it.t_enq for it in batch if it.t_enq), default=t_proc
        )
        n = len(batch)
        iters_override = None
        rung_name = None
        ladder = self._ladders.get(engine_name)
        if ladder is not None:
            from glom_tpu.resilience.ladder import RUNGS

            rung = ladder.rung()
            rung_name = RUNGS[rung]
            if rung >= self._degrade_gate(batch):
                iters_override = ladder.degraded_iters
        scfg = getattr(engine, "scfg", None)
        budget = getattr(engine, "auto_budget", None)
        tiered = (
            scfg is not None
            and getattr(scfg, "max_continuations", 0) > 0
            and getattr(engine, "iters_key", None) == "auto"
            and iters_override is None
            and budget is not None
        )
        # Session warm-start: a cold row carrying a session_id rides the
        # stream's cached columns when one is resident (full budget — a
        # new frame, not a continuation). Decided HERE, at dispatch, so
        # the state is the freshest write-back and a cache invalidated
        # since submit can never warm-start the row. PAGES mode: the hit
        # is a pinned PageHit — the row carries page INDICES into the
        # engine's paged signature and the columns never leave HBM; a
        # hit in a SIBLING's pool (affinity raced a failover) reads as a
        # miss here. Continuation groups skip lookups in pages mode (the
        # paged signature and the host levels0 carry are different
        # programs — folded fresh rows go cold, stamped as misses).
        pages_mode = (
            engine_name in self._pools
            and self.cache is not None
            and self.cache.pools is not None
        )
        # DELTA STREAMING (docs/SERVING.md, "Delta streaming"): a
        # delta-config pool stores base+Σdeltas chains; warm session rows
        # additionally compute their INPUT delta's page support (bitwise
        # vs the previous frame's host patches) and ride the engine's
        # incremental signature, where empty-support rows start
        # pre-converged. Threshold 0 disables the seeding (bitwise
        # contract) and the dispatch is the plain paged route.
        pool = self._pools.get(engine_name)
        delta_mode = (
            pages_mode and pool is not None and getattr(pool, "delta", False)
        )
        use_inc = (
            delta_mode
            and getattr(scfg, "delta_incremental", True)
            and getattr(engine, "iters_key", None) == "auto"
            and getattr(scfg, "exit_threshold", 0.0) > 0.0
            and iters_override is None
            and getattr(engine, "mesh", None) is None
        )
        has_cont = any(it.warm_src == "cont" for it in batch)
        n_cache_warm = n_cache_miss = 0
        hold_rows = None  # delta mode: rows whose input did not change
        pinned: List[str] = []
        if self.cache is not None:
            for it in batch:
                if (
                    it.levels is not None
                    or it.pages is not None
                    or it.session is None
                ):
                    continue
                if pages_mode:
                    if has_cont:
                        n_cache_miss += 1
                        continue
                    if delta_mode and it.patches is None:
                        # Once per row: the support comparison AND the
                        # next write-back's prev-input reference read
                        # these same host patches.
                        it.patches = _patchify_host(
                            it.img, engine.cfg.patch_size
                        )
                    hit = self.cache.lookup(it.session, pin=True)
                    full_n = engine.cfg.num_patches
                    if (
                        hit is not None
                        and getattr(hit, "engine", None) == engine_name
                        and getattr(hit, "n_tokens", None) == full_n
                    ):
                        it.pages = hit
                        it.warm_src = "pages"
                        pinned.append(it.session)
                        n_cache_warm += 1
                    else:
                        if hit is not None:
                            self.cache.unpin(it.session)
                        n_cache_miss += 1
                else:
                    hit = self.cache.lookup(it.session)
                    if hit is not None:
                        it.levels = hit
                        it.warm_src = "cache"
                        n_cache_warm += 1
                    else:
                        n_cache_miss += 1
        warm_pages = any(it.pages is not None for it in batch)
        warm = any(it.levels is not None for it in batch)
        # The remaining per-request budget caps the auto route at the
        # TIGHTEST row (min over rows of budget - executed; cold and
        # cache-warm rows have the full budget) — UNLESS a degraded
        # ladder rung pinned a fixed iters_override for this dispatch
        # (the engine rejects the combination: a fixed route has no
        # budget to cap, and the degraded budget already bounds cost).
        # Rows capped below their own remaining budget simply re-enter
        # the continuation queue with the difference — per-request
        # totals never exceed the budget.
        prior = max((it.executed for it in batch), default=0)
        try:
            bucket = engine.pick_bucket(n)
            imgs = np.zeros((bucket, *batch[0].img.shape), np.float32)
            for i, req in enumerate(batch):
                imgs[i] = req.img
            kw = {}
            if iters_override is not None:
                kw["iters_override"] = iters_override
            if warm:
                # Per-row levels0 select — the mixed warm/cold bucket:
                # warm rows carry their cached/mid-flight state, cold
                # rows the engine's own cold init (bitwise what the
                # forward would build itself; pad rows stay zeros — the
                # mask keeps them out of the witness either way).
                proto = next(it.levels for it in batch if it.levels is not None)
                lv0 = np.zeros((bucket, *proto.shape), proto.dtype)
                cold = None
                for i, it in enumerate(batch):
                    if it.levels is not None:
                        lv0[i] = it.levels
                    else:
                        if cold is None:
                            cold = np.asarray(engine.cold_levels())
                        lv0[i] = cold
                kw["levels0"] = lv0
                remaining = max(1, budget - prior) if budget else None
                if (
                    iters_override is None
                    and remaining is not None
                    and remaining < budget
                ):
                    kw["auto_budget"] = remaining
            elif warm_pages:
                # The PAGED warm path: rows carry page indices, cold
                # rows -1 — the compiled program takes the pool pages
                # in-graph (zero levels0 upload; serve/paged_columns.py).
                # In delta mode the indices are each session's EFFECTIVE
                # base+Σdeltas map — reconstruction is this same take.
                ppr = engine.cfg.num_patches // pool.page_tokens
                prow = np.full((bucket, ppr), -1, np.int32)
                for i, it in enumerate(batch):
                    if it.pages is not None:
                        prow[i] = it.pages.pages
                kw["page_rows"] = prow
                if use_inc:
                    # The incremental route's seed: warm rows carry
                    # their input delta's page support, cold/miss rows
                    # full support (they behave like plain tiered exit).
                    srow = np.zeros((bucket, ppr), bool)
                    for i, it in enumerate(batch):
                        if it.pages is not None and it.patches is not None:
                            srow[i] = self.cache.input_support(
                                it.session, it.patches, pool.page_tokens
                            )
                        else:
                            srow[i] = True
                    srow[n:] = False  # pad rows: masked out anyway
                    kw["support_rows"] = srow
                    # A HOLD frame (empty input support) also skips its
                    # write-back below: an unchanged input adds nothing
                    # worth storing, and one floor-iteration of drift
                    # written back every frame would churn delta pages
                    # (and force compactions that privatize shared
                    # bases) for state the next frame reconverges to
                    # anyway. The cache stays warm with the previous
                    # entry; prev_input is unchanged by construction.
                    hold_rows = [
                        bool(it.pages is not None and not srow[i].any())
                        for i, it in enumerate(batch)
                    ]
            pack_s = self._clock() - t_proc
            with span("serve_dispatch", aggregator=self.spans):
                result = engine.infer(imgs, n_valid=n, **kw)
            for sid in pinned:
                self.cache.unpin(sid)
            pinned = []
            t_fetch = self._clock()
            with span("serve_fetch", aggregator=self.spans):
                levels = np.asarray(result.levels[:n])
            fetch_s = self._clock() - t_fetch
        except BaseException as e:  # noqa: BLE001 — relayed per ticket
            for sid in pinned:
                self.cache.unpin(sid)
            self._handle_dispatch_failure(
                engine_name, batch, e, dspan, tfields, n, warm or warm_pages
            )
            return

        # Resolve vs re-bucket, row by row. Stragglers (valid, unconverged,
        # budget left, hops left) carry their warm state into the
        # continuation queue as ONE group; everyone else resolves with
        # their TOTAL executed iterations (per row now — a mixed bucket's
        # rows entered with different priors) and, when the row carries a
        # session, writes its converged columns back to the cache for the
        # stream's next frame. Draining stop() opens no new hops —
        # stragglers resolve with the state they have.
        conv = result.row_converged
        stragglers: List[_Item] = []
        resolved: List[dict] = []
        n_resolved = 0
        entry_tier = max((it.hops for it in batch), default=0)
        # This hop's wall span, as the dispatch record will carry it: the
        # items accumulate EXACTLY these values (latency_ms is the
        # left-to-right float sum of the rounded phase fields under
        # phase_split — see _phase_fields), in hop order, so the resolve
        # leaf's dispatch_ms_total AND per-phase phase_ms_total equal the
        # sums of its trace's per-hop fields bit-for-bit (the
        # conservation check in telemetry/tracectx.py is exact).
        phases, latency_ms = self._phase_fields(
            queue_wait_s, pack_s, result, fetch_s
        )
        to_resolve: List[tuple] = []  # (item, row index, total iters)
        for i, it in enumerate(batch):
            executed_i = it.executed + result.iters_run
            it.dispatch_ms += latency_ms
            self._note_item_phases(it, phases)
            if dspan is not None:
                it.parent_span = dspan  # the next record parents HERE
            open_hop = (
                tiered
                and conv is not None
                and not self._stop.is_set()
                and it.hops < scfg.max_continuations
                and executed_i < budget
            )
            if open_hop and not bool(conv[i]):
                it.levels = np.array(levels[i])
                it.executed = executed_i
                it.hops += 1
                it.warm_src = "cont"
                it.t_enq = self._clock()  # cont-queue wait starts now
                stragglers.append(it)
            else:
                # Write-back BEFORE resolve: the moment the caller sees
                # frame t's response it may submit frame t+1, and that
                # frame must find the cache already warm. Pages mode
                # hands the DEVICE row slice straight to the pool
                # (device-to-device — the converged columns never visit
                # the host on the way in).
                skip_store = bool(
                    hold_rows is not None and i < len(hold_rows)
                    and hold_rows[i]
                )
                if (
                    self.cache is not None
                    and it.session is not None
                    and not skip_store
                ):
                    if pages_mode:
                        ch = None
                        if delta_mode and not pool.holds(it.session):
                            # Content hash over the EXACT row bytes the
                            # pool will store: identical converged bases
                            # (two cameras, one scene) alias refcounted
                            # pool pages. Hashed from the host copy the
                            # resolve path already fetched — no extra
                            # transfer, and only on BASE creation (a
                            # session already holding a block appends
                            # deltas; the pool consumes no hash there, so
                            # hashing every frame would be pure resolve-
                            # path overhead).
                            import hashlib

                            ch = hashlib.sha256(
                                np.ascontiguousarray(levels[i]).tobytes()
                            ).hexdigest()
                        self.cache.store(
                            it.session, result.levels[i],
                            engine=engine_name,
                            n_tokens=engine.cfg.num_patches,
                            patches=it.patches if delta_mode else None,
                            content_hash=ch,
                        )
                    else:
                        self.cache.store(
                            it.session, np.array(levels[i]),
                            engine=engine_name,
                        )
                to_resolve.append((it, i, executed_i))
                resolved.append({"iters": executed_i, "tier": it.hops})
                n_resolved += 1
        if stragglers:
            self._cont_q.put(stragglers)
            worst = max(it.executed for it in stragglers)
            cont = {
                "event": "continuation",
                "engine": engine_name,
                "n_stragglers": len(stragglers),
                "executed_iters": worst,
                "remaining_budget": budget - worst,
                "hop": max(it.hops for it in stragglers),
                "trace_ids": (
                    [it.ticket.trace_id for it in stragglers]
                    if self._trace else None
                ),
            }
            if self._trace:
                cont["span_id"] = tracectx.new_span_id()
                cont["parent_spans"] = [dspan] * len(stragglers)
            self._emit(cont)
        n_page_warm = sum(1 for it in batch if it.warm_src == "pages")
        tok_bytes = self._token_state_bytes(engine)
        pad_tokens = None
        if getattr(engine, "cfg", None) is not None:
            pad_tokens = (result.bucket - n) * engine.cfg.num_patches
        rec = {
            "event": "dispatch",
            "engine": engine_name,
            "bucket": result.bucket,
            "n_valid": n,
            "warm_state": warm or warm_pages,
            "paged": warm_pages,
            "tier": entry_tier,
            "pad_fraction": round(1.0 - n / result.bucket, 4),
            "latency_ms": latency_ms,
            **phases,
            "iters_run": result.iters_run,
            "n_stragglers": len(stragglers),
            "n_cache_warm": n_cache_warm,
            "n_cache_miss": n_cache_miss,
            "n_page_warm": n_page_warm,
            "levels0_h2d_bytes": getattr(result, "levels0_h2d_bytes", 0),
            "compiled": result.compiled,
            **tfields,
        }
        if use_inc and warm_pages:
            # The incremental dispatch stamps its route and its explicit
            # tolerance (the compare gate reads delta_page_atol — 0.0
            # would be the bitwise mode, which never reaches this route).
            rec["incremental"] = True
            rec["n_incremental"] = n
            rec["delta_page_atol"] = pool.delta_page_atol
        if pad_tokens is not None:
            rec["pad_tokens"] = pad_tokens
            if tok_bytes is not None:
                rec["pad_bytes"] = pad_tokens * tok_bytes
        if rung_name is not None:
            rec["rung"] = rung_name
        if iters_override is not None:
            rec["iters_override"] = iters_override
        cls_rows = self._class_rows(batch)
        if cls_rows is not None:
            rec["classes"] = cls_rows
        # The dispatch log is read by summary_record() from the CALLER's
        # thread while this worker appends — glom-lint's lockset checker
        # flagged the bare append (iteration during append is a crash, not
        # just a stale read), so the batch log rides the counter lock
        # (nested inside the engine lock: see _note_dispatch).
        self._note_dispatch(
            engine_name, rec, resolved,
            n_served=n_resolved,
            n_degraded=n_resolved if iters_override is not None else 0,
            n_continued=len(stragglers),
            class_served=self._class_rows([t[0] for t in to_resolve]),
        )
        # Tickets resolve AFTER the counters: the instant result() returns
        # a caller may read summary_record(), and its conservation
        # (n_served + n_shed + n_failed == n_requests) must already hold.
        for it, i, executed_i in to_resolve:
            it.ticket._resolve(
                levels[i], executed_i,
                hops=it.hops, dispatch_ms=it.dispatch_ms,
            )
            if self._trace:
                # The RESOLVE leaf: one per-request record carrying the
                # served totals the trace tree must conserve against
                # (summed hop iters_run / latency_ms == these exactly).
                # Only minted when tracing — it exists for the tree, and
                # the trace-ab gate prices it.
                self._emit(
                    {
                        "event": "resolve",
                        "request_id": it.ticket.request_id,
                        "engine": engine_name,
                        "iters_total": executed_i,
                        "dispatch_ms_total": it.dispatch_ms,
                        # Per-phase accumulation across this request's
                        # hops (tracectx conservation reads it); null
                        # when phase_split is off, like the hop fields.
                        "phase_ms_total": (
                            dict(it.phase_ms) if self._phase_split
                            else None
                        ),
                        "hops": it.hops,
                        "redispatches": it.redispatches,
                        "latency_ms": round(1e3 * it.ticket._latency_s, 3),
                        "slo_class": it.ticket.slo_class,
                        "trace_id": it.ticket.trace_id,
                        "span_id": tracectx.new_span_id(),
                        "parent_span": dspan,
                    }
                )
        self._emit(rec)
        self._ladder_observe(engine_name)

    def _dispatch_ragged_batch(
        self, engine, engine_name: str, batch, dspan, tfields
    ) -> None:
        """One RAGGED dispatch (docs/SERVING.md, "Ragged admission"):
        rows of differing patch counts pack page-aligned onto a flat
        token axis sized by a ragged-ladder page count — no worst-row
        bucket shape, no pad rows. Warm state rides the page pool
        (session hits pin their pages and the dispatch carries indices)
        — EXCEPT continuation groups: straggler rows re-enter carrying
        their mid-flight columns as a flat levels0 with their REMAINING
        budget (ragged x continuation composition; a continuation's
        state is unresolved, so it has no pages to ride)."""
        from glom_tpu.serve.paged_columns import (
            pages_for_tokens,
            resolve_page_tokens,
        )

        t_proc = self._clock()
        queue_wait_s = t_proc - min(
            (it.t_enq for it in batch if it.t_enq), default=t_proc
        )
        n = len(batch)
        iters_override = None
        rung_name = None
        ladder = self._ladders.get(engine_name)
        if ladder is not None:
            from glom_tpu.resilience.ladder import RUNGS

            rung = ladder.rung()
            rung_name = RUNGS[rung]
            if rung >= self._degrade_gate(batch):
                iters_override = ladder.degraded_iters
        scfg = getattr(engine, "scfg", None)
        budget = getattr(engine, "auto_budget", None)
        tiered = (
            scfg is not None
            and getattr(scfg, "max_continuations", 0) > 0
            and getattr(engine, "iters_key", None) == "auto"
            and iters_override is None
            and budget is not None
        )
        has_cont = any(it.warm_src == "cont" for it in batch)
        pool = self._pools.get(engine_name)
        pages_mode = (
            pool is not None
            and self.cache is not None
            and self.cache.pools is not None
        )
        n_cache_warm = n_cache_miss = 0
        pinned: List[str] = []
        if self.cache is not None:
            for it in batch:
                if it.session is None or it.levels is not None:
                    continue
                if not pages_mode or has_cont:
                    # A host-array cache cannot warm a ragged dispatch
                    # (the route has no levels0 input by design — that
                    # is the transfer being killed), and a continuation
                    # group's dispatch is the levels0 program (pages do
                    # not compose with it — folded fresh rows go cold):
                    # stamped as a miss either way.
                    n_cache_miss += 1
                    continue
                hit = self.cache.lookup(it.session, pin=True)
                if (
                    hit is not None
                    and getattr(hit, "engine", None) == engine_name
                    and getattr(hit, "n_tokens", None) == it.n_patches
                ):
                    it.pages = hit
                    it.warm_src = "pages"
                    pinned.append(it.session)
                    n_cache_warm += 1
                else:
                    if hit is not None:
                        self.cache.unpin(it.session)
                    n_cache_miss += 1
        pt = (
            pool.page_tokens if pool is not None
            else resolve_page_tokens(engine.cfg, engine.scfg)
        )
        counts = [it.n_patches for it in batch]
        row_pages = [pages_for_tokens(c, pt) for c in counts]
        try:
            pages_sig = engine.pick_pages(sum(row_pages))
            T = pages_sig * pt
            flat = np.zeros((T, engine.cfg.patch_dim), np.float32)
            pidx = (
                np.full((pages_sig,), -1, np.int32)
                if pool is not None else None
            )
            starts = []
            off = 0
            for it, k in zip(batch, row_pages):
                start = off * pt
                starts.append(start)
                flat[start:start + it.n_patches] = _patchify_host(
                    it.img, engine.cfg.patch_size
                )
                if it.pages is not None:
                    pidx[off:off + k] = it.pages.pages
                off += k
            kw = {}
            if iters_override is not None:
                kw["iters_override"] = iters_override
            if has_cont:
                # Ragged x continuation composition: straggler rows carry
                # their mid-flight columns into the flat levels0 at their
                # row's page span; folded-in fresh rows take the engine's
                # cold init (bitwise what the forward would build itself;
                # pad slots stay zeros — the witness masks them). A cont
                # dispatch is the levels0 program — mutually exclusive
                # with page indices at the engine, so pidx is dropped.
                cold = np.asarray(engine.cold_levels())
                lv0 = np.zeros((T, *cold.shape[1:]), cold.dtype)
                for it, start in zip(batch, starts):
                    c = it.n_patches
                    if it.levels is not None:
                        lv0[start:start + c] = it.levels
                    else:
                        lv0[start:start + c] = cold[:c]
                kw["levels0"] = lv0
                pidx = None
                prior = max((it.executed for it in batch), default=0)
                remaining = max(1, budget - prior) if budget else None
                if (
                    iters_override is None
                    and remaining is not None
                    and remaining < budget
                ):
                    kw["auto_budget"] = remaining
            pack_s = self._clock() - t_proc
            with span("serve_dispatch", aggregator=self.spans):
                result = engine.infer_ragged(
                    flat, counts, page_idx=pidx, **kw
                )
            for sid in pinned:
                self.cache.unpin(sid)
            pinned = []
            t_fetch = self._clock()
            with span("serve_fetch", aggregator=self.spans):
                levels_flat = np.asarray(result.levels)
            fetch_s = self._clock() - t_fetch
        except BaseException as e:  # noqa: BLE001 — relayed per ticket
            for sid in pinned:
                self.cache.unpin(sid)
            self._handle_dispatch_failure(
                engine_name, batch, e, dspan, tfields, n, n_cache_warm > 0
            )
            return

        phases, latency_ms = self._phase_fields(
            queue_wait_s, pack_s, result, fetch_s
        )
        conv = result.row_converged
        stragglers: List[_Item] = []
        resolved: List[dict] = []
        n_resolved = 0
        entry_tier = max((it.hops for it in batch), default=0)
        to_resolve: List[tuple] = []
        for i, it in enumerate(batch):
            executed_i = it.executed + result.iters_run
            it.dispatch_ms += latency_ms
            self._note_item_phases(it, phases)
            if dspan is not None:
                it.parent_span = dspan
            open_hop = (
                tiered
                and conv is not None
                and not self._stop.is_set()
                and it.hops < scfg.max_continuations
                and executed_i < budget
            )
            if open_hop and not bool(conv[i]):
                # The straggler carries its ROW SPAN (the unit the
                # banded parity contract covers) into the continuation
                # queue; next hop it repacks page-aligned as a ragged
                # row with the remaining budget.
                it.levels = np.array(
                    levels_flat[starts[i]:starts[i] + it.n_patches]
                )
                it.executed = executed_i
                it.hops += 1
                it.warm_src = "cont"
                it.t_enq = self._clock()  # cont-queue wait starts now
                stragglers.append(it)
                continue
            # Write-back BEFORE resolve, device-to-device: the row's
            # converged columns go straight from the dispatch output
            # into owned pool pages (the next frame's warm state never
            # visits the host). Stragglers skip it — their state is
            # mid-flight, not a frame worth warming from.
            if pages_mode and it.session is not None:
                self.cache.store(
                    it.session,
                    result.levels[starts[i]:starts[i] + it.n_patches],
                    engine=engine_name,
                    n_tokens=it.n_patches,
                )
            row_levels = levels_flat[starts[i]:starts[i] + it.n_patches]
            to_resolve.append((it, row_levels, executed_i))
            resolved.append({"iters": executed_i, "tier": it.hops})
            n_resolved += 1
        if stragglers:
            self._cont_q.put(stragglers)
            worst = max(it.executed for it in stragglers)
            cont = {
                "event": "continuation",
                "engine": engine_name,
                "ragged": True,
                "n_stragglers": len(stragglers),
                "executed_iters": worst,
                "remaining_budget": budget - worst,
                "hop": max(it.hops for it in stragglers),
                "trace_ids": (
                    [it.ticket.trace_id for it in stragglers]
                    if self._trace else None
                ),
            }
            if self._trace:
                cont["span_id"] = tracectx.new_span_id()
                cont["parent_spans"] = [dspan] * len(stragglers)
            self._emit(cont)
        pad_tokens = T - sum(counts)
        tok_bytes = self._token_state_bytes(engine)
        rec = {
            "event": "dispatch",
            "engine": engine_name,
            "bucket": f"ragged{pages_sig}",
            "ragged": True,
            "n_valid": n,
            "n_pages": pages_sig,
            "n_tokens": sum(counts),
            "warm_state": n_cache_warm > 0 or has_cont,
            "paged": n_cache_warm > 0,
            "tier": entry_tier,
            # Token-based pad accounting: the ragged pad tax is the page
            # tails plus the ladder round-up — row axis padding is GONE.
            "pad_fraction": round(pad_tokens / T, 4),
            "pad_tokens": pad_tokens,
            "latency_ms": latency_ms,
            **phases,
            "iters_run": result.iters_run,
            "n_stragglers": len(stragglers),
            "n_cache_warm": n_cache_warm,
            "n_cache_miss": n_cache_miss,
            "n_page_warm": n_cache_warm,
            "levels0_h2d_bytes": getattr(result, "levels0_h2d_bytes", 0),
            "compiled": result.compiled,
            **tfields,
        }
        if tok_bytes is not None:
            rec["pad_bytes"] = pad_tokens * tok_bytes
        if rung_name is not None:
            rec["rung"] = rung_name
        if iters_override is not None:
            rec["iters_override"] = iters_override
        cls_rows = self._class_rows(batch)
        if cls_rows is not None:
            rec["classes"] = cls_rows
        self._note_dispatch(
            engine_name, rec, resolved,
            n_served=n_resolved,
            n_degraded=n_resolved if iters_override is not None else 0,
            n_continued=len(stragglers),
            class_served=self._class_rows([t[0] for t in to_resolve]),
        )
        for it, row_levels, iters in to_resolve:
            it.ticket._resolve(
                row_levels, iters,
                hops=it.hops, dispatch_ms=it.dispatch_ms,
            )
            if self._trace:
                self._emit(
                    {
                        "event": "resolve",
                        "request_id": it.ticket.request_id,
                        "engine": engine_name,
                        "iters_total": iters,
                        "dispatch_ms_total": it.dispatch_ms,
                        "phase_ms_total": (
                            dict(it.phase_ms) if self._phase_split
                            else None
                        ),
                        "hops": it.hops,
                        "redispatches": it.redispatches,
                        "latency_ms": round(1e3 * it.ticket._latency_s, 3),
                        "slo_class": it.ticket.slo_class,
                        "trace_id": it.ticket.trace_id,
                        "span_id": tracectx.new_span_id(),
                        "parent_span": dspan,
                    }
                )
        self._emit(rec)
        self._ladder_observe(engine_name)

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict, kind: str = "serve") -> None:
        from glom_tpu.serve.events import emit_serve

        stamped = emit_serve(self.writer, rec, kind=kind)
        for tap in list(self._taps):
            try:
                tap(stamped)
            except Exception:  # noqa: BLE001 — a tap never kills a worker
                pass

    def span_records(self, **extra) -> list:
        """Drain the serve-phase span rollups (one "span" record per phase
        seen since the last drain)."""
        return self.spans.records(extra=extra or None)

    def capacity_records(self) -> list:
        """One stamped "capacity" record per engine (schema v7,
        docs/OBSERVABILITY.md "Capacity observatory"): the signal the
        elastic-serving control loop (ROADMAP item 1) reads.

          * service_rate_rps — sustainable requests/s estimated from the
            engine's own dispatch evidence (valid rows served per second
            of dispatch wall — the per-bucket latency histograms'
            aggregate; None before the first dispatch);
          * queue/continuation/affinity/pool fills — LIVE occupancy of
            every lane a request can wait in, each normalized to [0, 1];
          * utilization — the WORST lane (capacity is gone when any lane
            saturates: a full pool blocks warm streams even with an
            empty queue);
          * headroom — 1 - utilization, clamped to [0, 1]; 0.0 for a
            dead engine (no capacity, whatever its queues say).

        `telemetry watch --slo headroom=X` breaches when headroom drops
        BELOW X — the one lower-bound rule.

        Every record stamps `state` ("ok" | "draining" | "probation" |
        "dead"): the SLO monitor EXCLUDES draining/probation engines
        from the headroom windowed-min (a deliberately draining engine's
        headroom would otherwise fire a permanent false breach that
        re-triggers the very autoscaler that caused it), and DRAINED
        engines emit no record at all — they left the fleet."""
        # Age-bounded husks retire on the capacity cadence (the
        # autoscaler calls this every tick), not only at the next drain.
        self._prune_husks()
        with self._engine_lock:  # LOCK ORDER: _engine_lock -> _counter_lock
            engines = {
                name: dict(st) for name, st in self._engine_state.items()
            }
            draining = set(self._draining)
            drained = set(self._drained)
            with self._counter_lock:
                dispatches = list(self.dispatches)
        qcap = max(1, self._q.maxsize)
        queue_fill = round(min(1.0, self._q.qsize() / qcap), 4)
        # The continuation lane holds GROUPS (lists of warm items): its
        # occupancy is the ITEM count — 8 queued bucket-8 groups are a
        # saturated lane, not 8/64 of one (stdlib Queue's mutex guards
        # the snapshot; the lane is unbounded, so the admission queue's
        # capacity is the normalizer).
        with self._cont_q.mutex:
            cont_items = sum(len(g) for g in self._cont_q.queue)
        cont_fill = round(min(1.0, cont_items / qcap), 4)
        out = []
        for i, eng in enumerate(self.engines):
            name = self._ename(eng, i)
            if name in drained:
                continue  # voluntarily left the fleet: no capacity record
            st = engines.get(name, {})
            own = [d for d in dispatches if d.get("engine") == name]
            # The service-rate denominator is ENGINE-BUSY time (h2d +
            # device + resolve), not latency_ms — which under
            # phase_split includes queue_wait, so at saturation (the
            # exact regime the autoscaler reads this) it would collapse
            # the estimate several-fold below what the engine sustains.
            # Dispatches without a phase split fall back to latency_ms
            # (there it IS the bare engine wall).
            busy_s = 0.0
            for d in own:
                parts = [
                    d.get(k) for k in ("h2d_ms", "device_ms", "resolve_ms")
                ]
                if all(isinstance(v, (int, float)) for v in parts):
                    busy_s += sum(parts) / 1e3
                elif isinstance(d.get("latency_ms"), (int, float)):
                    busy_s += d["latency_ms"] / 1e3
            served = sum(d.get("n_valid") or 0 for d in own)
            service_rate = (
                round(served / busy_s, 3) if busy_s > 0 else None
            )
            aq = self._aff_q.get(name)
            aff_fill = (
                round(min(1.0, aq.qsize() / max(1, aq.maxsize)), 4)
                if aq is not None else 0.0
            )
            pool = self._pools.get(name)
            pool_fill = None
            if pool is not None:
                pr = pool.record()
                total = pr.get("pages_total") or 0
                if total:
                    pool_fill = round(pr.get("pages_used", 0) / total, 4)
            alive = bool(st.get("alive", True))
            lanes = [queue_fill, cont_fill, aff_fill]
            if pool_fill is not None:
                lanes.append(pool_fill)
            utilization = round(max(lanes), 4)
            headroom = (
                0.0 if not alive
                else round(max(0.0, 1.0 - utilization), 4)
            )
            state = (
                "draining" if name in draining
                else "probation" if st.get("probation")
                else "ok" if alive
                else "dead"
            )
            cap_rec = {
                "engine": name,
                "alive": alive,
                "state": state,
                "headroom": headroom,
                "utilization": utilization,
                "service_rate_rps": service_rate,
                "queue_fill": queue_fill,
                "continuation_fill": cont_fill,
                "affinity_fill": aff_fill,
                "pool_fill": pool_fill,
                "n_dispatches": len(own),
            }
            if self._qos is not None:
                # Per-class LANE fill (qos.py ClassQueues): the elastic
                # loop needs to see WHICH tenant's lane is saturating —
                # aggregate queue_fill hides a full premium lane behind
                # an empty batch lane. Classless records keep the exact
                # pre-QoS shape (no key).
                cap_rec["class_fill"] = {
                    cn: round(
                        min(1.0, f["depth"] / max(1, f["capacity"])), 4
                    )
                    for cn, f in self._q.class_fill().items()
                }
            out.append(schema.stamp(cap_rec, kind="capacity"))
        return out

    def summary_record(self) -> dict:
        """The end-of-run "serve" summary event. The iteration histogram
        is PER REQUEST: each resolved request's TOTAL executed column
        iterations across all of its hops — the two-tier accounting unit
        (iters_histogram_by_tier splits it by how many continuation hops
        the request rode). Snapshot under both locks in the documented
        order: workers may still be serving while a caller summarizes,
        and the per-engine counts must be consistent with the global
        conservation counters."""
        with self._engine_lock:  # LOCK ORDER: _engine_lock -> _counter_lock
            engines = {
                name: dict(st) for name, st in self._engine_state.items()
            }
            # Drain-state annotation: added ONLY on fleets that actually
            # drained (the static path's engines nest stays byte-for-byte
            # the pre-elastic shape, pinned by tests).
            for name in self._draining:
                if name in engines:
                    engines[name]["draining"] = True
            for name in self._drained:
                if name in engines:
                    engines[name]["drained"] = True
            with self._counter_lock:
                elastic = self._elastic
                dispatches = list(self.dispatches)
                hist = dict(self._iters_hist)
                by_tier = {
                    t: dict(h) for t, h in self._iters_hist_by_tier.items()
                }
                iters_total = self._iters_total
                n_requests = self.n_requests
                n_submitted = self.n_submitted
                n_served = self.n_served
                n_shed = self.n_shed
                n_failed = self.n_failed
                n_degraded = self.n_degraded
                n_continued = self.n_continued
                n_redispatched = self.n_redispatched
                n_folded = self.n_folded
                n_rejoined = self.n_rejoined
                n_affinity = self.n_affinity
                n_page_warm = self.n_page_warm
                n_incremental = self.n_incremental
                pad_fraction_sum = self._pad_fraction_sum
                pad_bytes_wasted = self._pad_bytes_wasted
                levels0_h2d_bytes = self._levels0_h2d_bytes
                phase_sums = dict(self._phase_sums)
                class_counts = {
                    c: dict(v) for c, v in self._class_counts.items()
                }
            husks_retired = dict(self._husks_retired)
        rec = {
            "event": "summary",
            "n_requests": n_requests,
            "n_submitted": n_submitted,
            "n_served": n_served,
            "n_shed": n_shed,
            "n_failed": n_failed,
            "n_degraded": n_degraded,
            "n_continued": n_continued,
            "n_redispatched": n_redispatched,
            "n_folded": n_folded,
            "n_rejoined": n_rejoined,
            "n_affinity": n_affinity,
            "n_page_warm": n_page_warm,
            "n_incremental": n_incremental,
            "n_dispatches": len(dispatches),
            # Pad-tax rollup (mean dispatch pad fraction + the bytes the
            # padding wasted) and the warm-path upload total — the pair
            # the ragged bench's CI gate and `telemetry compare` read
            # (pad regresses UP as a cost; levels0_h2d_bytes must be 0
            # on the paged warm path).
            "pad_fraction_mean": round(
                pad_fraction_sum / len(dispatches), 4
            ) if dispatches else 0.0,
            "pad_bytes_wasted": pad_bytes_wasted,
            "levels0_h2d_bytes": levels0_h2d_bytes,
            # Mean GATHERED batch size: valid rows per dispatch (a warm
            # continuation hop is a dispatch too) — n_served would skew
            # it, since a straggler's rows resolve on a LATER dispatch
            # than the one that gathered them.
            "mean_batch": round(
                sum(d["n_valid"] for d in dispatches) / len(dispatches), 3
            ) if dispatches else 0.0,
            "iters_histogram": hist,
            "iters_histogram_by_tier": by_tier,
            "mean_executed_iters": round(
                iters_total / n_served, 3
            ) if n_served else None,
            "engines": engines,
        }
        if class_counts or self._qos is not None:
            # Per-tenant conservation (ISSUE 19): each class's counters
            # must reconcile on their own — n_served + n_shed + n_failed
            # == n_requests PER CLASS, not just in aggregate. Classless
            # runs add no key (bit-parity with the pre-QoS summary).
            classes = {}
            for cls in sorted(class_counts):
                cnt = dict(class_counts[cls])
                cnt["served_fraction"] = (
                    round(cnt["n_served"] / cnt["n_requests"], 4)
                    if cnt["n_requests"] else None
                )
                classes[cls] = cnt
            rec["classes"] = classes
            if self._qos is not None:
                # The admission scheduler's own evidence: pick counts,
                # floor preemptions, per-lane rejections.
                rec["class_scheduler"] = self._q.record()
        if husks_retired.get("n"):
            # Retention trimmed the engines nest: the folded counters
            # keep the books whole (global dispatch totals == the nest's
            # sum + these) — added only when a husk actually retired, so
            # unbounded-retention summaries keep the pre-v9 shape.
            rec["husks_retired"] = husks_retired
        if dispatches and phase_sums:
            # The latency decomposition rollup: MEAN ms per phase per
            # dispatch (the same five fields every dispatch record splits
            # latency_ms into, so p99 investigations start from the
            # summary and drill into `telemetry trace`). Compare flattens
            # these as serve_latency.* cost rows.
            from glom_tpu.telemetry.tracectx import PHASE_KEYS

            rec["latency_phases"] = {
                k: round(phase_sums.get(k, 0.0) / len(dispatches), 3)
                for k in PHASE_KEYS
            }
        # The capacity/headroom rollup, emitted as standalone "capacity"
        # records on EVERY summary (the watch --slo headroom tail reads
        # the stream) and nested here for the compare gate.
        cap = self.capacity_records()
        if cap:
            rec["capacity"] = {
                c["engine"]: {
                    "headroom": c["headroom"],
                    "utilization": c["utilization"],
                    "service_rate_rps": c["service_rate_rps"],
                }
                for c in cap
            }
            for c in cap:
                self._emit(c, kind="capacity")
        if self.cache is not None:
            # The streaming column cache's rollup (hits/misses/evictions/
            # bytes vs budget) — the temporal bench and its CI gate read
            # this nest (docs/OBSERVABILITY.md, cache metrics).
            rec["column_cache"] = self.cache.record()
        if self._pools:
            # The page pools' rollup (capacity/churn in live-bytes form;
            # pages_used + pages_free == pages_total is the conservation
            # pair the churn test reads).
            rec["page_pools"] = {
                name: pool.record() for name, pool in self._pools.items()
            }
        if elastic is not None:
            # The autoscaler's rollup (serve/elastic.py): scale counts,
            # spawn latency, migration totals, and the fleet-size
            # timeline — `telemetry compare` flattens it as
            # serve_elastic.* rows (spawn_ms / migrated_bytes as costs).
            rec["elastic"] = elastic.record()
        # Ladder/retry rollups: flat on a single-engine summary (the PR 6
        # record shape, pinned by tests), NESTED per engine under
        # `engines` on fan-out — a flat merge would let the last engine's
        # ladder_rung/n_retries overwrite every sibling's evidence.
        for i, eng in enumerate(self.engines):
            name = self._ename(eng, i)
            ladder = self._ladders.get(name)
            retry = getattr(eng, "retry", None)
            if len(self.engines) == 1:
                if ladder is not None:
                    rec.update(ladder.record())
                if retry is not None:
                    rec.update(retry.record())
            else:
                if ladder is not None:
                    rec["engines"][name]["ladder"] = ladder.record()
                if retry is not None:
                    rec["engines"][name]["retry"] = retry.record()
        return schema.stamp(rec, kind="serve")
