"""Host-side dynamic batching: bounded queue, bucket padding, shed path.

TPU serving economics are batch economics: one column-update of a batch-8
bucket costs barely more than batch-1 (the MXU is latency-bound at tiny
batches), so the host's job is to GATHER concurrent requests into bucket
shapes without letting the gathering itself become the latency. The
classic admission policy does it with two knobs:

  * max_batch — dispatch the moment this many requests are waiting (the
    throughput ceiling; never above the engine's largest bucket);
  * max_delay_ms — dispatch anyway once the OLDEST waiting request has
    aged this long (the latency floor: a lone 3am request pays at most
    max_delay_ms of gathering, not forever).

Gathered requests pad up to the smallest admitting bucket (the engine only
ever sees precompiled shapes — no mid-traffic recompiles) with a validity
mask, so pad rows neither reach callers nor vote on the consensus
early-exit witness (serve/early_exit.masked_level_agreement).

Failure discipline (the PR 2/3 lesson — a wedged backend must fail FAST
and leave evidence, never hang):

  * the request queue is BOUNDED: a submit against a full queue sheds
    immediately with QueueFullError (backpressure to the caller, who can
    retry/downgrade) and a stamped "serve" shed event carrying the WHY
    (queue depth/capacity, ladder rung);
  * when the global backend watchdog says "down", submissions and any
    already-gathered requests fail fast with BackendDownError, and each
    emits a schema "error" record carrying the machine-readable cause —
    the serving analog of sinks.bench_bootstrap's UNMEASURED record. A
    FLAPPING backend is NOT down: it keeps serving (degraded via the
    ladder; dispatch failures retry per the engine's RetryPolicy);
  * a dispatch exception fails ONLY that batch's requests (each ticket
    re-raises it) and the worker keeps serving;
  * with a DegradationLadder attached (glom_tpu/resilience/ladder.py),
    pressure and flap step serving DOWN one reversible rung at a time —
    capped iterations, then capped batches, then (last) shed — so
    shedding is the floor of the ladder, not the only move.

Host phases ride tracing.spans (SERVE_PHASES: serve_enqueue, serve_batch,
serve_dispatch, serve_fetch), aggregated per phase and drained by
span_records() — the same <1%-overhead rollup form the fit loop uses.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from glom_tpu.telemetry import schema
from glom_tpu.tracing.spans import SpanAggregator, span


class ShedError(RuntimeError):
    """Base of the fast-fail admission errors (never a hang). `detail`
    carries the machine-readable why (queue depth, ladder rung) — the
    same fields the stamped shed record gets, so a caller's except block
    and the telemetry stream read one story."""

    def __init__(self, message: str, **detail):
        super().__init__(message)
        self.detail = detail


class QueueFullError(ShedError):
    """Bounded queue at capacity: backpressure, retry later."""


class BackendDownError(ShedError):
    """The backend watchdog reports the accelerator down."""


class LadderShedError(ShedError):
    """The degradation ladder's last rung: every cheaper serving mode is
    already exhausted (glom_tpu/resilience/ladder.py)."""


class Ticket:
    """One request's future: result() blocks until served or failed."""

    def __init__(self, request_id):
        self.request_id = request_id
        self._done = threading.Event()
        self._levels: Optional[np.ndarray] = None
        self._iters_run: Optional[int] = None
        self._latency_s: Optional[float] = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()

    def _resolve(self, levels, iters_run):
        self._levels = levels
        self._iters_run = iters_run
        self._latency_s = time.perf_counter() - self.t_submit
        self._done.set()

    def _fail(self, exc: BaseException):
        self._error = exc
        self._latency_s = time.perf_counter() - self.t_submit
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """(levels [n, L, d], iters_run, latency_s) for THIS request, or
        re-raises the failure. latency_s is submit-to-resolve wall time —
        queueing + gathering + dispatch + fetch, the number the user felt."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not served within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._levels, self._iters_run, self._latency_s


class _Request:
    __slots__ = ("img", "ticket")

    def __init__(self, img: np.ndarray, ticket: Ticket):
        self.img = img
        self.ticket = ticket


def _backend_down() -> bool:
    from glom_tpu.telemetry.watchdog import backend_record

    return backend_record().get("backend_state") == "down"


class DynamicBatcher:
    """The admission scheduler in front of an InferenceEngine.

    Lifecycle: use as a context manager (or start()/stop()). submit() is
    thread-safe and returns a Ticket; a single worker thread gathers,
    pads, and dispatches. `engine` needs .infer(imgs, n_valid) ->
    ServeResult and .pick_bucket(n) — the tests drive the policy with a
    fake engine, no device required.
    """

    def __init__(
        self,
        engine,
        *,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        writer=None,
        shed_when_down: bool = True,
        ladder=None,
        clock=time.perf_counter,
    ):
        scfg = getattr(engine, "scfg", None)
        self.engine = engine
        self.max_batch = (
            max_batch if max_batch is not None
            else (scfg.max_batch if scfg else 8)
        )
        self.max_delay_s = (
            max_delay_ms if max_delay_ms is not None
            else (scfg.max_delay_ms if scfg else 5.0)
        ) / 1e3
        depth = (
            queue_depth if queue_depth is not None
            else (scfg.queue_depth if scfg else 64)
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch {self.max_batch} must be >= 1")
        self.writer = writer
        self.shed_when_down = shed_when_down
        # Degradation ladder (glom_tpu/resilience/ladder.py) — opt-in:
        # when attached, the worker feeds it queue pressure + backend
        # state each cycle, a capped_iters-or-worse rung dispatches with
        # the degraded fixed budget, a bucket_cap-or-worse rung gathers
        # smaller batches, and the shed rung fails NEW admissions fast
        # (the last resort, after the cheaper modes). ladder=None
        # RESOLVES from the engine's ServeConfig (scfg.ladder=True builds
        # one — a config that asks for the ladder must never be silently
        # two-mode); pass an explicit instance to own the knobs.
        if (
            ladder is None
            and scfg is not None
            and getattr(scfg, "ladder", False)
            and getattr(engine, "cfg", None) is not None
        ):
            from glom_tpu.resilience.ladder import DegradationLadder

            ladder = DegradationLadder.from_config(
                engine.cfg, scfg, writer=writer
            )
        self.ladder = ladder
        self._clock = clock
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.spans = SpanAggregator()
        # Counters for the end-of-run summary record. n_requests counts
        # every submit() ATTEMPT (n_submitted only the admitted ones), so
        # chaos runs can assert conservation: every request is served,
        # shed, or failed — never lost, never hung.
        self.n_requests = 0
        self.n_submitted = 0
        self.n_served = 0
        self.n_shed = 0
        self.n_failed = 0
        self.n_degraded = 0  # requests served on a capped-iters rung
        self.dispatches: List[dict] = []  # one dict per dispatched batch
        self._counter_lock = threading.Lock()
        self._seq = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DynamicBatcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._worker, name="glom-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the worker. drain=True serves what is already queued first
        (the graceful path); False fails queued requests FAST — the queue
        is drained and every ticket failed BEFORE waiting on the worker,
        so at most the one in-flight batch dispatches after the call.
        Also safe on a never-started batcher: queued tickets are failed
        (drain=False) — there is no worker to ever resolve them."""
        self._stop.set()
        if not drain:
            self._fail_queued()
        if self._thread is not None:
            # drain=True: the worker exits once the stop flag is set AND
            # the queue is empty — queued work is served on the way out.
            self._thread.join(timeout=60.0)
            self._thread = None
        # Whatever is STILL queued (drain=True with a dead/timed-out
        # worker, or a never-started batcher) can no longer resolve.
        self._fail_queued()

    def _fail_queued(self) -> None:
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            # Counted as FAILED: these tickets were admitted (n_submitted
            # incremented) and can no longer resolve — without the count,
            # summary_record()'s conservation (n_served + n_shed +
            # n_failed == n_requests) silently loses them.
            with self._counter_lock:
                self.n_failed += 1
            req.ticket._fail(ShedError("batcher stopped"))

    def __enter__(self) -> "DynamicBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission --------------------------------------------------------

    def submit(self, img) -> Ticket:
        """Enqueue one [c, H, W] request. Sheds immediately (raises) when
        the queue is full, the backend is down, or the degradation ladder
        is on its shed rung — admission never blocks the caller. Requests
        submitted before start() queue up and are served once the worker
        runs; stop() fails whatever can no longer resolve, so a ticket is
        never silently stranded."""
        with self._counter_lock:
            self._seq += 1
            rid = self._seq
            self.n_requests += 1
        ticket = Ticket(rid)
        with span("serve_enqueue", aggregator=self.spans):
            if self.shed_when_down and _backend_down():
                detail = self._pressure()
                self._shed(ticket, "backend-down", **detail)
                raise BackendDownError(
                    "backend watchdog reports the accelerator down; "
                    "request shed (fast-fail, never a hang)",
                    **detail,
                )
            if self.ladder is not None:
                from glom_tpu.resilience.ladder import SHED

                if self.ladder.rung() >= SHED:
                    detail = self._pressure()
                    self._shed(ticket, "ladder-shed", **detail)
                    raise LadderShedError(
                        "degradation ladder at its shed rung (every "
                        "cheaper serving mode exhausted); retry later",
                        **detail,
                    )
            img = np.asarray(img, np.float32)
            # Count the admission BEFORE the put (rolled back on a full
            # queue): the instant the request is enqueued the worker may
            # serve it, and n_served must never exceed n_submitted even
            # transiently (the race harness caught both orderings that
            # counted after the put as off-by-ones).
            with self._counter_lock:
                self.n_submitted += 1
            try:
                self._q.put_nowait(_Request(img, ticket))
            except queue.Full:
                with self._counter_lock:
                    self.n_submitted -= 1
                detail = self._pressure()
                self._shed(ticket, "queue-full", **detail)
                raise QueueFullError(
                    f"request queue at capacity ({self._q.maxsize}); "
                    "backpressure — retry later",
                    **detail,
                ) from None
            if self._stop.is_set() and (
                self._thread is None or not self._thread.is_alive()
            ):
                # Race with stop(): the put landed after the (dead or
                # never-started) worker's final drain — no one will ever
                # dispatch it, so fail it here rather than strand the
                # ticket. A LIVE draining worker still owns the queue.
                self._fail_queued()
                raise ShedError("batcher stopped")
        return ticket

    def _pressure(self) -> dict:
        """The machine-readable WHY of a shed/ladder decision: queue depth
        and capacity, plus the ladder rung when one is attached — these
        fields ride both the stamped record and the raised exception
        (before this, the shed path lost the why)."""
        detail = {
            "queue_depth": self._q.qsize(),
            "queue_capacity": self._q.maxsize,
        }
        if self.ladder is not None:
            detail["rung"] = self.ladder.rung_name()
        return detail

    def _shed(self, ticket: Ticket, reason: str, **detail) -> None:
        with self._counter_lock:
            self.n_shed += 1
        exc_type = {
            "backend-down": BackendDownError,
            "ladder-shed": LadderShedError,
        }.get(reason, QueueFullError)
        ticket._fail(exc_type(reason, **detail))
        # The shed decision itself is a "serve" event carrying the why
        # (queue depth / ladder rung; stamp_serve merges backend_state);
        # a backend-down shed ALSO emits the schema "error" record (value
        # null, machine-readable cause) — the same UNMEASURED discipline
        # as the benches.
        self._emit(
            {
                "event": "shed",
                "reason": reason,
                "request_id": ticket.request_id,
                **detail,
            }
        )
        if reason == "backend-down":
            self._emit(
                {
                    "error": "backend-down",
                    "value": None,
                    "request_id": ticket.request_id,
                    "note": "request shed: backend watchdog reports down",
                },
                kind="error",
            )

    # -- the worker --------------------------------------------------------

    def _ladder_observe(self) -> None:
        """Feed the ladder one (pressure, backend) observation. Runs every
        worker cycle — INCLUDING idle ones, so a drained queue steps the
        ladder back up even when no traffic arrives to dispatch."""
        if self.ladder is None:
            return
        from glom_tpu.telemetry.watchdog import backend_record

        fill = self._q.qsize() / max(1, self._q.maxsize)
        self.ladder.observe(
            queue_fill=fill,
            backend_state=backend_record().get("backend_state", "unknown"),
        )

    def _gather(self) -> List[_Request]:
        """Block for the first request, then gather until max_batch or the
        first request ages past max_delay — the two-knob admission. A
        ladder at bucket_cap or worse gathers smaller batches: smaller,
        faster dispatches drain a backed-up queue in bounded bites."""
        max_batch = self.max_batch
        if self.ladder is not None:
            from glom_tpu.resilience.ladder import BUCKET_CAP

            if self.ladder.rung() >= BUCKET_CAP:
                max_batch = min(max_batch, self.ladder.bucket_cap)
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = self._clock() + self.max_delay_s
        while len(batch) < max_batch:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _worker(self) -> None:
        while not (self._stop.is_set() and self._q.empty()):
            self._ladder_observe()
            with span("serve_batch", aggregator=self.spans):
                batch = self._gather()
            if not batch:
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        n = len(batch)
        if self.shed_when_down and _backend_down():
            # Gathered but undispatchable: fail every ticket fast with the
            # stamped evidence — never dispatch into a dead backend (the
            # round-5 hang this subsystem exists to never reproduce).
            for req in batch:
                self._shed(req.ticket, "backend-down", **self._pressure())
            return
        iters_override = None
        rung_name = None
        if self.ladder is not None:
            from glom_tpu.resilience.ladder import CAPPED_ITERS, RUNGS

            rung = self.ladder.rung()
            rung_name = RUNGS[rung]
            if rung >= CAPPED_ITERS:
                iters_override = self.ladder.degraded_iters
        try:
            bucket = self.engine.pick_bucket(n)
            imgs = np.zeros((bucket, *batch[0].img.shape), np.float32)
            for i, req in enumerate(batch):
                imgs[i] = req.img
            kw = {} if iters_override is None else {
                "iters_override": iters_override
            }
            with span("serve_dispatch", aggregator=self.spans):
                result = self.engine.infer(imgs, n_valid=n, **kw)
            with span("serve_fetch", aggregator=self.spans):
                levels = np.asarray(result.levels[:n])
        except BaseException as e:  # noqa: BLE001 — relayed per ticket
            with self._counter_lock:
                self.n_failed += len(batch)
            for req in batch:
                req.ticket._fail(e)
            self._emit(
                {
                    "event": "dispatch_error",
                    "n_valid": n,
                    "exception": f"{type(e).__name__}: {e}"[:300],
                }
            )
            return
        for i, req in enumerate(batch):
            req.ticket._resolve(levels[i], result.iters_run)
        rec = {
            "event": "dispatch",
            "bucket": result.bucket,
            "n_valid": n,
            "pad_fraction": round(1.0 - n / result.bucket, 4),
            "latency_ms": round(1e3 * result.latency_s, 3),
            "iters_run": result.iters_run,
            "compiled": result.compiled,
        }
        if rung_name is not None:
            rec["rung"] = rung_name
        if iters_override is not None:
            rec["iters_override"] = iters_override
        # The dispatch log is read by summary_record() from the CALLER's
        # thread while this worker appends — glom-lint's lockset checker
        # flagged the bare append (iteration during append is a crash, not
        # just a stale read), so the batch log rides the counter lock.
        with self._counter_lock:
            self.n_served += n
            if iters_override is not None:
                self.n_degraded += n
            self.dispatches.append(rec)
        self._emit(rec)
        self._ladder_observe()

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict, kind: str = "serve") -> None:
        from glom_tpu.serve.events import emit_serve

        emit_serve(self.writer, rec, kind=kind)

    def span_records(self, **extra) -> list:
        """Drain the serve-phase span rollups (one "span" record per phase
        seen since the last drain)."""
        return self.spans.records(extra=extra or None)

    def summary_record(self) -> dict:
        """The end-of-run "serve" summary event. The iteration histogram
        is PER REQUEST (each of a dispatch's n_valid requests ran its
        batch's iteration count) — the early-exit accounting unit.
        Snapshot under the counter lock: the worker may still be serving
        while a caller summarizes, and the counters must be mutually
        consistent (n_served vs the dispatch log it was derived from)."""
        with self._counter_lock:
            dispatches = list(self.dispatches)
            n_requests = self.n_requests
            n_submitted = self.n_submitted
            n_served = self.n_served
            n_shed = self.n_shed
            n_failed = self.n_failed
            n_degraded = self.n_degraded
        hist: dict = {}
        for d in dispatches:
            key = str(d["iters_run"])
            hist[key] = hist.get(key, 0) + d["n_valid"]
        rec = {
            "event": "summary",
            "n_requests": n_requests,
            "n_submitted": n_submitted,
            "n_served": n_served,
            "n_shed": n_shed,
            "n_failed": n_failed,
            "n_degraded": n_degraded,
            "n_dispatches": len(dispatches),
            "mean_batch": round(
                n_served / len(dispatches), 3
            ) if dispatches else 0.0,
            "iters_histogram": hist,
        }
        if self.ladder is not None:
            rec.update(self.ladder.record())
        retry = getattr(self.engine, "retry", None)
        if retry is not None:
            rec.update(retry.record())
        return schema.stamp(rec, kind="serve")
