"""Serving: batched inference with AOT warmup and consensus early exit.

The training stack ends at a checkpoint; this subsystem is what stands
between that checkpoint and traffic (docs/SERVING.md). Layers:

    engine     — InferenceEngine: params + one AOT-compiled forward per
                 (bucket, iters-route, warm/cold) signature, explicit
                 warmup(), donated input buffers, per-bucket latency
                 histograms; ServeConfig.mesh_data/.mesh_seq route every
                 signature through the sharded (data x seq) shard_map
                 forward (parallel/serve_mesh.py)
    batcher    — DynamicBatcher: bounded request queue, max_batch /
                 max_delay_ms admission, pad-to-bucket with mask, the
                 continuation queue (two-tier stragglers re-bucketed
                 warm), multi-engine fan-out with dead-engine failover,
                 and the fast-fail shed path wired to the watchdog
    column_cache — ColumnCache: session-keyed warm-start column state
                 (streaming: frame t+1 dispatches from frame t's
                 converged columns), LRU under an HBM-priced byte
                 budget, TTL, invalidation on engine failure; PAGES
                 mode makes entries page-table references into the pool
    paged_columns — PagedColumnPool: the device-resident HBM page pool
                 (one preallocated [pages, page_tokens, L, d] buffer per
                 engine + host page table) behind the zero-transfer warm
                 path and ragged admission
    elastic    — ElasticPolicy + Autoscaler: the SLO-driven control loop
                 that spawns fully-warmed replicas at runtime and
                 gracefully drains them back out (capacity follows load
                 — docs/SERVING.md "Elastic serving")
    qos        — SLOClass / QosSpec / ClassQueues: named SLO classes
                 with deficit-weighted-fair admission (strict priority
                 bounded by a starvation floor), class-aware
                 degradation/shed, and per-class telemetry
                 (docs/SERVING.md "SLO classes")
    early_exit — glom_forward_auto / glom_forward_tiered: lax.while_loop
                 over column updates with the consensus-agreement delta
                 as the stopping witness (iters="auto"; the tiered form
                 is per-row + quorum — static max_iters keeps shapes
                 fixed either way)
    cli        — `python -m glom_tpu.serve`: the stdin/file micro-server

Re-exports are LAZY (PEP 562, same pattern as glom_tpu/telemetry): the
batcher's shed errors and ServeConfig must be importable without paying
the jax import, and engine/early_exit pull jax only when actually used.
"""

_EXPORTS = {
    "InferenceEngine": "engine",
    "RaggedServeResult": "engine",
    "ServeResult": "engine",
    "BackendDownError": "batcher",
    "DynamicBatcher": "batcher",
    "LadderShedError": "batcher",
    "QueueFullError": "batcher",
    "ShedError": "batcher",
    "Ticket": "batcher",
    "Autoscaler": "elastic",
    "ElasticPolicy": "elastic",
    "SCALE_EVENTS": "elastic",
    "resolve_policy": "elastic",
    "ClassQueues": "qos",
    "QosSpec": "qos",
    "SLOClass": "qos",
    "class_slo_rules": "qos",
    "parse_slo_class": "qos",
    "resolve_slo_classes": "qos",
    "ColumnCache": "column_cache",
    "PageHit": "column_cache",
    "column_state_bytes": "column_cache",
    "resolve_column_cache": "column_cache",
    "PagedColumnPool": "paged_columns",
    "page_state_bytes": "paged_columns",
    "pages_for_tokens": "paged_columns",
    "resolve_page_pool": "paged_columns",
    "resolve_page_tokens": "paged_columns",
    "RaggedResult": "early_exit",
    "TieredAutoResult": "early_exit",
    "batch_agreement": "early_exit",
    "glom_forward_auto": "early_exit",
    "glom_forward_ragged": "early_exit",
    "glom_forward_tiered": "early_exit",
    "masked_level_agreement": "early_exit",
    "ragged_row_layout": "early_exit",
    "emit_serve": "events",
    "stamp_serve": "events",
}
_SUBMODULES = ("batcher", "cli", "column_cache", "early_exit", "elastic",
               "engine", "events", "paged_columns", "qos", "workload")

__all__ = sorted([*_EXPORTS, *_SUBMODULES])


def __getattr__(name):
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f"glom_tpu.serve.{name}")
    if name in _EXPORTS:
        module = importlib.import_module(f"glom_tpu.serve.{_EXPORTS[name]}")
        return getattr(module, name)
    raise AttributeError(f"module 'glom_tpu.serve' has no attribute {name!r}")
