"""Workload observatory: capture traffic, replay it deterministically.

The capacity observatory (PR 13) and autoscaler (PR 14) can say what the
fleet DID, but not what the traffic WAS — so elastic scenarios are
unreproducible and a forecast (telemetry/forecast.py) has nothing
honest to train or score against. This module closes that gap with one
artifact: a schema-v9 `"workload"` JSONL stream, one record per OFFERED
request — arrival time `t` (seconds, run-relative), shape `signature`
("bucket:CxHxW" | "ragged:<N>p" | "delta:CxHxW"), `session`, and
`outcome` ("served" | "shed" | "failed" | "unresolved" | "offered").

Three producers, one consumer:

  * `WorkloadRecorder` rides the batcher event tap
    (DynamicBatcher.add_event_tap) and stitches per-request admission
    ("admit"), shed, and terminal ("settle"/"resolve") events into the
    artifact — recordable from any live server or bench run
    (`--record-workload`).
  * The scenario generators (`gen_diurnal`, `gen_flash_crowd`,
    `gen_rolling_outage`) synthesize the same artifact from a seed —
    pure stdlib (random + math), outcome "offered", so chaos-grade
    elastic scenarios are reproducible from JSONL alone.
  * `replay()` re-offers any artifact with faithful inter-arrival
    pacing and session structure (`bench_serve.py --replay`,
    `python -m glom_tpu.serve --replay`). Clock and sleep are
    injectable, so the tier-1 round-trip test drives a fake clock and
    asserts pacing exactly — no wall-clock flake.

The artifact lints like any other stream (`python -m glom_tpu.telemetry
FILE`): a "note" header names the source, the "workload" body carries
the requests, a "summary" trailer carries the counts.
"""

from __future__ import annotations

import json
import math
import random
import threading
import time
import zlib
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from glom_tpu.telemetry import schema

OUTCOMES = ("served", "shed", "failed", "unresolved", "offered")


# -- capture ---------------------------------------------------------------


class WorkloadRecorder:
    """Stitch the batcher's per-request evidence into a workload artifact.

    attach() arms the batcher's admission events
    (enable_admission_events) and subscribes this recorder as an event
    tap; from then on every submit lands one entry ("unresolved" until
    its terminal arrives), every shed/settle flips the entry's outcome.
    Thread-safe: taps fire from submit AND worker threads concurrently,
    and records() snapshots under the same lock, so a mid-traffic
    snapshot still satisfies conservation over what it saw."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_rid: dict = {}   # request_id -> mutable entry
        self._order: list = []    # request_ids in admission order
        self._t_first: Optional[float] = None

    def attach(self, batcher) -> "WorkloadRecorder":
        batcher.enable_admission_events()
        batcher.add_event_tap(self.observe)
        return self

    def observe(self, rec: dict) -> None:
        """The event tap: consumes the stamped batcher stream; ignores
        everything that is not per-request evidence."""
        if rec.get("kind") != "serve":
            return
        event = rec.get("event")
        rid = rec.get("request_id")
        if rid is None:
            return
        with self._lock:
            if event == "admit":
                if self._t_first is None:
                    self._t_first = float(rec["t"])
                if rid not in self._by_rid:
                    self._order.append(rid)
                self._by_rid[rid] = {
                    "t": float(rec["t"]),
                    "signature": rec.get("signature"),
                    "shape": rec.get("shape"),
                    "session": rec.get("session"),
                    "slo_class": rec.get("slo_class"),
                    "outcome": "unresolved",
                }
            elif event == "shed":
                entry = self._by_rid.get(rid)
                if entry is not None:
                    entry["outcome"] = "shed"
                    entry["reason"] = rec.get("reason")
            elif event == "settle":
                entry = self._by_rid.get(rid)
                if entry is not None and entry["outcome"] == "unresolved":
                    entry["outcome"] = rec.get("outcome", "served")
            elif event == "resolve":
                # Traced runs mint a resolve leaf too — same terminal,
                # idempotent with the settle event either order.
                entry = self._by_rid.get(rid)
                if entry is not None and entry["outcome"] == "unresolved":
                    entry["outcome"] = "served"

    @property
    def n_offered(self) -> int:
        with self._lock:
            return len(self._order)

    def records(self) -> List[dict]:
        """The artifact body: stamped "workload" records in admission
        order, arrival times normalized run-relative (t=0 at the first
        admission) so a replay needs no epoch arithmetic."""
        with self._lock:
            t0 = self._t_first or 0.0
            out = []
            for i, rid in enumerate(self._order):
                e = self._by_rid[rid]
                rec = {
                    "t": round(e["t"] - t0, 6),
                    "signature": e["signature"],
                    "outcome": e["outcome"],
                    "request_id": rid,
                    "seed": i,
                    "session": e["session"],
                    "shape": e["shape"],
                    # v11: the class key is PRESENT on every workload
                    # record (null = classless) so a replay re-offers
                    # each request under ITS tenant.
                    "slo_class": e.get("slo_class"),
                }
                if e.get("reason") is not None:
                    rec["reason"] = e["reason"]
                out.append(schema.stamp(rec, kind="workload"))
            return out

    def summary(self) -> dict:
        """Outcome counts over what was captured — the artifact's
        conservation trailer (offered == served + shed + failed +
        unresolved, exactly)."""
        with self._lock:
            counts = {k: 0 for k in OUTCOMES}
            for e in self._by_rid.values():
                counts[e["outcome"]] = counts.get(e["outcome"], 0) + 1
            counts["n_offered"] = len(self._order)
            return counts

    def write(self, path: str, *, source: str = "recorder") -> int:
        """Write the full artifact (note header + body + summary
        trailer); returns how many workload records landed."""
        recs = self.records()
        write_workload(path, recs, source=source, summary=self.summary())
        return len(recs)


def write_workload(
    path: str,
    records: Sequence[dict],
    *,
    source: str,
    summary: Optional[dict] = None,
) -> None:
    """One lintable artifact: "note" header (provenance), "workload"
    body, "summary" trailer (outcome conservation)."""
    with open(path, "w") as fh:
        header = schema.stamp(
            {"note": f"workload artifact: {source}", "n_requests": len(records)},
            kind="note",
        )
        fh.write(json.dumps(header) + "\n")
        for rec in records:
            fh.write(json.dumps(schema.stamp(rec, kind="workload")) + "\n")
        trailer = dict(summary) if summary is not None else _count(records)
        fh.write(
            json.dumps(schema.stamp(trailer, kind="summary")) + "\n"
        )


def _count(records: Sequence[dict]) -> dict:
    counts = {k: 0 for k in OUTCOMES}
    for r in records:
        counts[r.get("outcome", "offered")] = (
            counts.get(r.get("outcome", "offered"), 0) + 1
        )
    counts["n_offered"] = len(records)
    return counts


# -- replay ----------------------------------------------------------------


def load_workload(path: str) -> List[dict]:
    """The replayable body of an artifact: its "workload" records in
    arrival order. Loud on an artifact with none — replaying an empty
    workload silently "passing" is the failure mode this observatory
    exists to kill."""
    with open(path) as fh:
        recs = [
            r for _, r in schema.iter_json_lines(fh)
            if r.get("kind") == "workload"
        ]
    for r in recs:
        errs = schema.validate_record(r)
        if errs:
            raise ValueError(f"workload record invalid: {errs[0]}")
    if not recs:
        raise ValueError(f"{path}: no workload records to replay")
    recs.sort(key=lambda r: float(r["t"]))
    return recs


def _shape_of(rec: dict) -> Tuple[int, ...]:
    """The input shape to synthesize: the explicit `shape` field when
    recorded, else parsed from a bucket/delta signature. A ragged record
    without `shape` is unreplayable (the page count alone does not pick
    H x W) — loud, not guessed."""
    shape = rec.get("shape")
    if shape:
        return tuple(int(d) for d in shape)
    sig = str(rec.get("signature") or "")
    mode, _, dims = sig.partition(":")
    if mode in ("bucket", "delta") and dims:
        return tuple(int(d) for d in dims.split("x"))
    raise ValueError(
        f"workload record t={rec.get('t')} signature={sig!r} carries no "
        "replayable shape (ragged signatures need the recorded `shape`)"
    )


def synth_input(rec: dict, index: int = 0) -> np.ndarray:
    """Deterministic input synthesis for one workload record: stateless
    requests are pure seeded gaussians; a session's frames are small
    perturbations of ITS base image (the temporal-coherence assumption
    the column cache exploits) — the same construction as the serve
    CLI's frame_img, so a replayed stream exercises the warm path the
    original did."""
    shape = _shape_of(rec)
    seed = int(rec.get("seed", index))

    def rng(s: int) -> np.ndarray:
        return np.random.default_rng(s).normal(size=shape).astype(np.float32)

    session = rec.get("session")
    if session is None:
        return rng(seed)
    base = rng(zlib.crc32(str(session).encode()) & 0x7FFFFFFF)
    return base + 0.05 * rng((1 << 20) + seed)


def replay(
    records: Sequence[dict],
    submit: Callable[[dict, int], object],
    *,
    time_scale: float = 1.0,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """Re-offer a workload with faithful inter-arrival pacing.

    `submit(rec, index)` offers one request (bench/CLI wrap
    batcher.submit(synth_input(rec, i), session_id=rec["session"]));
    a raise from submit counts as shed-at-admission — the replay
    drives ON through it, because the original traffic did not stop
    for a shed either. time_scale stretches (>1) or compresses (<1)
    the recorded gaps; clock/sleep are injectable so tests replay on a
    fake clock with zero wall time.

    Returns pacing evidence: n_offered / n_submitted / n_shed, plus
    the max and mean scheduling lag (how late each offer fired vs its
    recorded arrival, in ms) — the "pacing within tolerance" number
    the round-trip test asserts on."""
    if time_scale <= 0:
        raise ValueError(f"time_scale {time_scale} must be > 0")
    records = list(records)
    t_wall0 = clock()
    t_rec0 = float(records[0]["t"]) if records else 0.0
    n_offered = n_submitted = n_shed = 0
    lag_sum = lag_max = 0.0
    for i, rec in enumerate(records):
        target = (float(rec["t"]) - t_rec0) * time_scale
        now = clock() - t_wall0
        if target > now:
            sleep(target - now)
        lag = max(0.0, (clock() - t_wall0) - target)
        lag_sum += lag
        lag_max = max(lag_max, lag)
        n_offered += 1
        try:
            submit(rec, i)
            n_submitted += 1
        except Exception:  # noqa: BLE001 — a shed is data, not a stop
            n_shed += 1
    return {
        "n_offered": n_offered,
        "n_submitted": n_submitted,
        "n_shed": n_shed,
        "pacing_lag_mean_ms": round(
            1e3 * lag_sum / n_offered, 3
        ) if n_offered else 0.0,
        "pacing_lag_max_ms": round(1e3 * lag_max, 3),
        "duration_s": round(clock() - t_wall0, 6),
    }


# -- scenario generators (pure stdlib) -------------------------------------


def parse_class_mix(spec) -> Optional[dict]:
    """'premium=0.2,batch=0.5' -> {"premium": 0.2, "batch": 0.5}: the
    --class-mix knob. Fractions are per-class probabilities; they must
    sum to <= 1 and the remainder is UNCLASSED traffic (slo_class null
    — the server's default class catches it). None/empty spec = a
    classless scenario, byte-identical to the pre-v11 generators."""
    if spec is None or (isinstance(spec, str) and not spec.strip()):
        return None
    if isinstance(spec, dict):
        mix = {str(k): float(v) for k, v in spec.items()}
    else:
        mix = {}
        for part in str(spec).split(","):
            name, eq, val = part.partition("=")
            name = name.strip()
            if not name or not eq:
                raise ValueError(
                    f"class mix entry {part!r}: expected NAME=FRACTION"
                )
            try:
                mix[name] = mix.get(name, 0.0) + float(val)
            except ValueError:
                raise ValueError(
                    f"class mix entry {part!r}: fraction {val!r} is not "
                    "a number"
                ) from None
    for name, f in mix.items():
        if not 0.0 <= f <= 1.0:
            raise ValueError(
                f"class mix {name}={f}: fraction must be in [0, 1]"
            )
    if sum(mix.values()) > 1.0 + 1e-9:
        raise ValueError(
            f"class mix fractions sum to {sum(mix.values()):.4f} > 1"
        )
    return mix


def _deal_class(class_mix: Optional[dict], rng: random.Random):
    """One deterministic class draw (sorted names, cumulative walk) —
    None both for classless scenarios and for the unclassed remainder."""
    if not class_mix:
        return None
    u = rng.random()
    acc = 0.0
    for name in sorted(class_mix):
        acc += class_mix[name]
        if u < acc:
            return name
    return None


def _signature_for(
    shape: Tuple[int, ...],
    session: Optional[str],
    *,
    mode: str,
    patch_size: Optional[int] = None,
    page_tokens: Optional[int] = None,
) -> str:
    dims = "x".join(str(int(d)) for d in shape)
    if mode == "ragged":
        if not (patch_size and page_tokens):
            raise ValueError(
                "ragged scenarios need patch_size= and page_tokens= to "
                "price the page signature"
            )
        c, h, w = shape
        tokens = (h // patch_size) * (w // patch_size)
        pages = max(1, math.ceil(tokens / page_tokens))
        return f"ragged:{pages}p"
    if mode == "delta" and session is not None:
        return f"delta:{dims}"
    return f"bucket:{dims}"


def _arrivals(
    rate_fn: Callable[[float], float],
    duration_s: float,
    rate_max: float,
    rng: random.Random,
) -> List[float]:
    """Nonhomogeneous Poisson arrivals by Lewis thinning: candidates at
    the peak rate, kept with probability rate(t)/rate_max — exact for
    any bounded intensity curve, and deterministic per seed."""
    ts: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            return ts
        if rng.random() * rate_max < rate_fn(t):
            ts.append(t)


def _materialize(
    ts: Iterable[float],
    *,
    streams: int,
    shapes: Sequence[Tuple[int, ...]],
    mode: str,
    rng: random.Random,
    patch_size: Optional[int],
    page_tokens: Optional[int],
    keep: Callable[[float, Optional[str]], bool] = lambda t, s: True,
    class_mix: Optional[dict] = None,
) -> List[dict]:
    """Arrival times -> stamped "workload" records: sessions dealt
    round-robin (the serve CLI's stream convention), shapes drawn per
    request (mixed-resolution ragged traffic needs more than one),
    SLO classes dealt per the --class-mix fractions (parse_class_mix;
    the unclassed remainder stays null), and a keep() predicate for
    scenarios that silence part of the traffic."""
    out: List[dict] = []
    i = 0
    for t in ts:
        session = f"s{i % streams}" if streams > 0 else None
        shape = shapes[rng.randrange(len(shapes))] if len(shapes) > 1 else (
            shapes[0]
        )
        slo_class = _deal_class(class_mix, rng)
        i += 1
        if not keep(t, session):
            continue
        out.append(
            schema.stamp(
                {
                    "t": round(t, 6),
                    "signature": _signature_for(
                        shape, session, mode=mode,
                        patch_size=patch_size, page_tokens=page_tokens,
                    ),
                    "outcome": "offered",
                    "seed": len(out),
                    "session": session,
                    "shape": list(shape),
                    "slo_class": slo_class,
                },
                kind="workload",
            )
        )
    return out


def gen_diurnal(
    duration_s: float = 10.0,
    *,
    base_rps: float = 5.0,
    peak_rps: float = 30.0,
    period_s: Optional[float] = None,
    seed: int = 0,
    streams: int = 4,
    shapes: Sequence[Tuple[int, ...]] = ((1, 28, 28),),
    mode: str = "bucket",
    patch_size: Optional[int] = None,
    page_tokens: Optional[int] = None,
    class_mix: Optional[dict] = None,
) -> List[dict]:
    """The daily curve, compressed: arrival rate swings sinusoidally
    base -> peak -> base over period_s (default: the whole duration is
    one period). The forecast's seasonality component exists for exactly
    this shape."""
    if peak_rps < base_rps:
        raise ValueError(f"peak_rps {peak_rps} < base_rps {base_rps}")
    period = period_s if period_s is not None else duration_s
    rng = random.Random(seed)

    def rate(t: float) -> float:
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period))
        return base_rps + (peak_rps - base_rps) * phase

    ts = _arrivals(rate, duration_s, peak_rps, rng)
    return _materialize(
        ts, streams=streams, shapes=shapes, mode=mode, rng=rng,
        patch_size=patch_size, page_tokens=page_tokens,
        class_mix=parse_class_mix(class_mix),
    )


def gen_flash_crowd(
    duration_s: float = 10.0,
    *,
    base_rps: float = 5.0,
    crowd_rps: float = 50.0,
    t_start: Optional[float] = None,
    crowd_s: Optional[float] = None,
    seed: int = 0,
    streams: int = 4,
    shapes: Sequence[Tuple[int, ...]] = ((1, 28, 28),),
    mode: str = "bucket",
    patch_size: Optional[int] = None,
    page_tokens: Optional[int] = None,
    class_mix: Optional[dict] = None,
) -> List[dict]:
    """The step the autoscaler dreads: steady base load, then a crowd
    arrives all at once for crowd_s seconds (default: the middle third
    of the run) — the no-warning shape where spawn lead time IS the
    outage window."""
    if crowd_rps < base_rps:
        raise ValueError(f"crowd_rps {crowd_rps} < base_rps {base_rps}")
    start = t_start if t_start is not None else duration_s / 3.0
    width = crowd_s if crowd_s is not None else duration_s / 3.0
    rng = random.Random(seed)

    def rate(t: float) -> float:
        return crowd_rps if start <= t < start + width else base_rps

    ts = _arrivals(rate, duration_s, crowd_rps, rng)
    return _materialize(
        ts, streams=streams, shapes=shapes, mode=mode, rng=rng,
        patch_size=patch_size, page_tokens=page_tokens,
        class_mix=parse_class_mix(class_mix),
    )


def gen_rolling_outage(
    duration_s: float = 10.0,
    *,
    rps: float = 20.0,
    outage_start: Optional[float] = None,
    outage_s: Optional[float] = None,
    seed: int = 0,
    streams: int = 4,
    shapes: Sequence[Tuple[int, ...]] = ((1, 28, 28),),
    mode: str = "bucket",
    patch_size: Optional[int] = None,
    page_tokens: Optional[int] = None,
    class_mix: Optional[dict] = None,
) -> List[dict]:
    """A partial outage ROLLS across the stream population: each session
    group goes dark for its own slice of the outage window (group k
    silent during the k-th sub-window), then returns — the
    partially-correlated dip that fools a naive trend fit and the shape
    scale-in must NOT chase."""
    if streams < 1:
        raise ValueError("gen_rolling_outage needs streams >= 1")
    start = outage_start if outage_start is not None else duration_s / 4.0
    width = outage_s if outage_s is not None else duration_s / 2.0
    slice_s = width / streams
    rng = random.Random(seed)

    def keep(t: float, session: Optional[str]) -> bool:
        if session is None or not (start <= t < start + width):
            return True
        k = int(session[1:]) % streams
        return not (
            start + k * slice_s <= t < start + (k + 1) * slice_s
        )

    ts = _arrivals(lambda t: rps, duration_s, rps, rng)
    return _materialize(
        ts, streams=streams, shapes=shapes, mode=mode, rng=rng,
        patch_size=patch_size, page_tokens=page_tokens, keep=keep,
        class_mix=parse_class_mix(class_mix),
    )


SCENARIOS = {
    "diurnal": gen_diurnal,
    "flash-crowd": gen_flash_crowd,
    "rolling-outage": gen_rolling_outage,
}


def generate(name: str, duration_s: float = 10.0, *, seed: int = 0, **kw):
    """Scenario library entry point: `generate("flash-crowd", 8.0,
    seed=3)` -> stamped workload records, identical for identical
    arguments (the whole point)."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}"
        ) from None
    return fn(duration_s, seed=seed, **kw)
