"""Consensus-convergence early exit: stop iterating when the columns settle.

GLOM's forward is iterative settling — T is a budget, not a requirement.
The per-level consensus agreement the telemetry subsystem already computes
in-graph (telemetry/diagnostics.level_agreement, the "islands of agreement"
formation signal) doubles as a stopping witness: when one more column
update no longer moves any level's agreement by more than a threshold, the
columns have converged and further iterations are wasted serving latency.

`glom_forward_auto` is the fixed-`iters` forward (models/core.glom_forward)
with the `lax.scan` replaced by a `lax.while_loop`:

  * the loop body is the SAME `update_step` (same ops, same order, same
    dtype discipline), so threshold=0.0 — where the exit condition can
    never fire (the agreement delta is >= 0, the test is strict <) — runs
    exactly `max_iters` iterations and reproduces the fixed-`iters` output
    BITWISE (locked by tests/test_serve.py);
  * `max_iters` is STATIC: shapes stay fixed, the program compiles once per
    bucket signature, and a non-converging input is bounded — the while
    loop only ever exits EARLY, never runs long;
  * the witness is the max-over-levels absolute delta of the [L] agreement
    vector between consecutive iterations, computed on the state the body
    already holds (one extra [L] reduction per iteration — the same cost
    telemetry_level="full" pays per training step);
  * `valid_mask` restricts the witness to real requests: a serving batch
    padded to its bucket must not let the PAD rows (which converge
    instantly — a constant image collapses to one island) vote the batch
    out of the loop early, nor hold it in.

The trade against the scanned forward: a while loop cannot be unrolled or
pipelined as aggressively by XLA, and autodiff does not apply — this is an
INFERENCE form (glom_tpu/serve), not a training path.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from einops import rearrange

from glom_tpu.models.core import contribution_divisor, update_step
from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.ops.patch import image_to_tokens
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.helpers import (
    TOKEN_ATTEND_SELF_VALUE,
    exists,
    l2norm,
    max_neg_value,
)


def batch_agreement(levels: jnp.ndarray) -> jnp.ndarray:
    """Per-image, per-level consensus agreement from a state [b, n, L, d]:
    mean over n of the cosine between each patch's level vector and that
    image's mean vector at the same level -> [b, L] float32. The batch
    mean of this is exactly diagnostics.level_agreement; serving keeps the
    batch axis so pad rows can be masked out of the stopping witness."""
    x = levels.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    mean = jnp.mean(xhat, axis=1, keepdims=True)  # [b, 1, L, d]
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    return jnp.mean(jnp.sum(xhat * mhat, axis=-1), axis=1)  # [b, L]


def masked_level_agreement(
    levels: jnp.ndarray, valid_mask: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """[L] agreement over the VALID rows only (all rows when mask is None).
    With an all-true mask this equals diagnostics.level_agreement exactly
    (same reductions, grouped batch-last instead of batch-first)."""
    per_image = batch_agreement(levels)  # [b, L]
    if valid_mask is None:
        return jnp.mean(per_image, axis=0)
    w = valid_mask.astype(jnp.float32)[:, None]  # [b, 1]
    return jnp.sum(per_image * w, axis=0) / jnp.maximum(jnp.sum(w), 1.0)


def _validate_auto_args(T: int, min_iters: int, threshold: float) -> None:
    if T < 1:
        raise ValueError(f"max_iters={T} must be >= 1")
    if not 1 <= min_iters <= T:
        raise ValueError(f"min_iters={min_iters} outside 1..{T}")
    if threshold < 0:
        raise ValueError(f"threshold={threshold} must be >= 0")


def _build_update_step(params, img, cfg, levels, compute_dtype, use_pallas):
    """The shared prologue of the auto forwards: cast once, patchify,
    build the per-iteration update closure. Returns (step(lv) -> new_lv,
    levels0) with the SAME ops in the same order as glom_forward's — the
    threshold-0 bitwise contract both loop forms inherit."""
    if use_pallas:
        from glom_tpu.kernels import fused_grouped_ffw

        ffw_fn = fused_grouped_ffw
    else:
        from glom_tpu.ops.ffw import grouped_ffw

        ffw_fn = grouped_ffw

    local_mask = build_local_mask(cfg.num_patches_side, cfg.local_consensus_radius)
    consensus_fn = partial(
        consensus_attention,
        attend_self=cfg.consensus_self,
        local_mask=local_mask,
    )

    # Identical prologue to glom_forward: cast ONCE, outside the loop.
    if compute_dtype is not None:
        params = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
        img = img.astype(compute_dtype)
        if exists(levels):
            levels = levels.astype(compute_dtype)

    with jax.named_scope("image_to_tokens"):
        tokens = image_to_tokens(params.token_embed, img, cfg.patch_size)
    b, n, d = tokens.shape
    pos = rearrange(params.pos_emb, "n d -> 1 n 1 d")
    bottom = rearrange(tokens, "b n d -> b n 1 d")

    if not exists(levels):
        levels = jnp.broadcast_to(
            params.init_levels[None, None], (b, n, cfg.levels, d)
        ).astype(tokens.dtype)

    divisor = contribution_divisor(cfg.levels, jnp.float32)

    def step(lv):
        return update_step(
            params, lv, bottom, pos, divisor,
            consensus_fn=consensus_fn, ffw_fn=ffw_fn,
        )

    return step, levels


def glom_forward_auto(
    params,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    levels: Optional[jnp.ndarray] = None,
    valid_mask: Optional[jnp.ndarray] = None,
    compute_dtype=None,
    use_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The early-exit GLOM forward: up to `max_iters` column updates,
    stopping once the agreement delta drops below `threshold`.

    Returns (final_levels [b, n, L, d], iters_run int32 scalar,
    agreement [L] float32 of the final state). `min_iters` floors the exit
    (at least that many updates always run); `threshold=0.0` disables the
    exit entirely — the strict `delta < threshold` test can then never
    pass and exactly `max_iters` updates run, bitwise-equal to
    glom_forward(iters=max_iters).

    use_pallas swaps the grouped-FFW for the fused Pallas kernel (which
    auto-falls back to the XLA form off-TPU); consensus stays the dense op
    — the serving engine compiles per bucket, and the reference-layout
    body keeps the exit witness identical across routes.
    """
    T = max_iters if max_iters is not None else cfg.default_iters
    _validate_auto_args(T, min_iters, threshold)
    step, levels = _build_update_step(
        params, img, cfg, levels, compute_dtype, use_pallas
    )
    thr = jnp.float32(threshold)

    def cond(carry):
        _, _, i, done = carry
        return jnp.logical_and(i < T, jnp.logical_not(done))

    def body(carry):
        lv, prev_agree, i, _ = carry
        new = step(lv)
        agree = masked_level_agreement(new, valid_mask)  # [L] f32
        delta = jnp.max(jnp.abs(agree - prev_agree))
        done = jnp.logical_and(i + 1 >= min_iters, delta < thr)
        return new, agree, i + 1, done

    init_agree = masked_level_agreement(levels, valid_mask)
    final, agree, iters_run, _ = jax.lax.while_loop(
        cond, body, (levels, init_agree, jnp.int32(0), jnp.bool_(False))
    )
    return final, iters_run, agree


class TieredAutoResult(NamedTuple):
    """One tiered auto forward's outcome (all jax arrays, still on device).

    `row_converged`/`row_iters` are PER ROW: whether each row's own
    agreement delta dropped below threshold, and the update count at which
    it first did (rows that never converged carry `iters_run`). Every row
    physically executes `iters_run` updates — row_iters is the *needed*
    count, iters_run the *executed* one (the number the serving histogram
    charges)."""

    levels: jnp.ndarray        # [b, n, L, d]
    iters_run: jnp.ndarray     # int32 scalar
    agreement: jnp.ndarray     # [L] float32 (valid rows only)
    row_converged: jnp.ndarray # [b] bool
    row_iters: jnp.ndarray     # [b] int32


def row_agreement_delta(
    agree_rows: jnp.ndarray, prev_rows: jnp.ndarray
) -> jnp.ndarray:
    """Per-row stopping witness: max over levels of the absolute agreement
    move between consecutive iterations. [b, L] x2 -> [b] float32."""
    return jnp.max(jnp.abs(agree_rows - prev_rows), axis=-1)


def quorum_need(quorum: float, n_valid: jnp.ndarray) -> jnp.ndarray:
    """ceil(quorum * n_valid) as an int32 scalar, floored at 1 — the
    converged-row count at which a bucket may exit. Computed in-graph so
    n_valid can come from a traced mask sum (the sharded form psums it)."""
    need = jnp.ceil(jnp.float32(quorum) * n_valid.astype(jnp.float32))
    return jnp.maximum(need.astype(jnp.int32), 1)


def glom_forward_tiered(
    params,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    quorum: float = 1.0,
    levels: Optional[jnp.ndarray] = None,
    valid_mask: Optional[jnp.ndarray] = None,
    compute_dtype=None,
    use_pallas: bool = False,
) -> TieredAutoResult:
    """The two-tier early-exit forward: the same update loop as
    glom_forward_auto, with the stopping witness made PER ROW and the exit
    condition a QUORUM — the bucket exits once ceil(quorum * n_valid)
    valid rows have individually converged (each row's own max-over-levels
    agreement delta below `threshold`, after `min_iters`). Converged rows
    keep updating until the bucket exits (the update is row-independent,
    so the extra iterations only settle them further); unconverged rows at
    exit are the STRAGGLERS the batcher re-buckets with their warm state
    (`levels=`) and the remaining budget.

    threshold=0.0 keeps the PR 4 contract: no row can ever converge
    (strict `delta < 0`), the loop runs exactly `max_iters`, and the final
    state is bitwise-equal to glom_forward(iters=max_iters) — the quorum
    never gets a vote. Pad rows (valid_mask False) neither count toward
    the quorum nor against it, whatever state they carry.
    """
    T = max_iters if max_iters is not None else cfg.default_iters
    _validate_auto_args(T, min_iters, threshold)
    step, levels = _build_update_step(
        params, img, cfg, levels, compute_dtype, use_pallas
    )
    b = levels.shape[0]
    valid = (
        jnp.ones((b,), bool) if valid_mask is None else valid_mask.astype(bool)
    )
    validf = valid.astype(jnp.float32)
    need = quorum_need(quorum, jnp.sum(validf))
    thr = jnp.float32(threshold)

    def cond(carry):
        lv, prev_rows, i, conv, row_iters = carry
        n_conv = jnp.sum(jnp.logical_and(conv, valid).astype(jnp.int32))
        return jnp.logical_and(i < T, n_conv < need)

    def body(carry):
        lv, prev_rows, i, conv, row_iters = carry
        new = step(lv)
        agree_rows = batch_agreement(new)  # [b, L] f32
        delta = row_agreement_delta(agree_rows, prev_rows)  # [b]
        newly = jnp.logical_and(i + 1 >= min_iters, delta < thr)
        first = jnp.logical_and(newly, jnp.logical_not(conv))
        row_iters = jnp.where(first, i + 1, row_iters)
        return new, agree_rows, i + 1, jnp.logical_or(conv, newly), row_iters

    init_rows = batch_agreement(levels)
    final, agree_rows, iters_run, conv, row_iters = jax.lax.while_loop(
        cond,
        body,
        (
            levels,
            init_rows,
            jnp.int32(0),
            jnp.zeros((b,), bool),
            jnp.full((b,), T, jnp.int32),
        ),
    )
    # Rows that never converged executed (and still need) iters_run.
    row_iters = jnp.where(conv, row_iters, iters_run)
    agreement = masked_level_agreement(final, valid_mask)
    return TieredAutoResult(final, iters_run, agreement, conv, row_iters)


def support_agreement(
    levels: jnp.ndarray, support: jnp.ndarray
) -> jnp.ndarray:
    """Per-row [b, L] consensus agreement restricted to the SUPPORT token
    positions ([b, n] bool — the input delta's page support expanded to
    tokens): batch_agreement's reduction with both the mean direction and
    the cosine average taken over support tokens only, so the witness
    watches exactly the columns the frame perturbed. Rows with EMPTY
    support read 0.0 at every level (constant across iterations — their
    delta is 0, which is what "pre-converged" means to the exit test)."""
    x = levels.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    w = support.astype(jnp.float32)[:, :, None, None]  # [b, n, 1, 1]
    cnt = jnp.maximum(jnp.sum(w, axis=(1, 2, 3)), 1.0)  # [b]
    mean = jnp.sum(xhat * w, axis=1, keepdims=True) / cnt[:, None, None, None]
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    cos = jnp.sum(xhat * mhat, axis=-1)  # [b, n, L]
    return (
        jnp.sum(cos * support.astype(jnp.float32)[:, :, None], axis=1)
        / cnt[:, None]
    )


def glom_forward_incremental(
    params,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    quorum: float = 1.0,
    levels: Optional[jnp.ndarray] = None,
    support_mask: Optional[jnp.ndarray] = None,
    valid_mask: Optional[jnp.ndarray] = None,
    compute_dtype=None,
    use_pallas: bool = False,
) -> TieredAutoResult:
    """The SPARSE INCREMENTAL warm forward (docs/SERVING.md, "Delta
    streaming"): glom_forward_tiered seeded from the input delta's page
    support. `support_mask` [b, n] marks the token positions whose INPUT
    changed since the frame that produced `levels`:

      * rows with EMPTY support (a hold frame — bitwise-identical input)
        start PRE-CONVERGED: they count toward the quorum from iteration
        zero and pay exactly the `min_iters` floor when the whole bucket
        is clean;
      * rows WITH support iterate under a witness computed ON the support
        (support_agreement) — the perturbed region's re-settling is what
        gates the exit, so a small perturbation converges in ~1-2 iters
        instead of re-running the full warm width whose global witness
        keeps moving while the change propagates.

    threshold == 0.0 is the BITWISE contract: the support seeding is
    disabled entirely (a Python-level branch, decided at trace time) and
    the call is glom_forward_tiered — no row ever converges, exactly
    max_iters updates run, bit-for-bit the full warm path. Any
    threshold > 0 mode is approximate BY THE STAMPED TOLERANCE: the
    un-supported columns' drift is bounded by the same exit threshold the
    auto route already accepts."""
    if threshold == 0.0 or support_mask is None:
        return glom_forward_tiered(
            params, img, cfg,
            max_iters=max_iters, threshold=threshold, min_iters=min_iters,
            quorum=quorum, levels=levels, valid_mask=valid_mask,
            compute_dtype=compute_dtype, use_pallas=use_pallas,
        )
    T = max_iters if max_iters is not None else cfg.default_iters
    _validate_auto_args(T, min_iters, threshold)
    step, levels = _build_update_step(
        params, img, cfg, levels, compute_dtype, use_pallas
    )
    b = levels.shape[0]
    valid = (
        jnp.ones((b,), bool) if valid_mask is None else valid_mask.astype(bool)
    )
    support = support_mask.astype(bool)
    row_dirty = jnp.any(support, axis=1)  # [b]
    need = quorum_need(quorum, jnp.sum(valid.astype(jnp.float32)))
    thr = jnp.float32(threshold)

    def cond(carry):
        lv, prev_rows, i, conv, row_iters = carry
        n_conv = jnp.sum(jnp.logical_and(conv, valid).astype(jnp.int32))
        # The min_iters FLOOR must live in the loop condition here: an
        # all-clean bucket is pre-converged before the first update, and
        # an empty-delta frame still owes its floor iterations (the
        # satellite contract tests/test_delta_cache.py pins).
        return jnp.logical_and(
            i < T, jnp.logical_or(i < min_iters, n_conv < need)
        )

    def body(carry):
        lv, prev_rows, i, conv, row_iters = carry
        new = step(lv)
        agree_rows = support_agreement(new, support)  # [b, L]
        delta = row_agreement_delta(agree_rows, prev_rows)
        newly = jnp.logical_and(i + 1 >= min_iters, delta < thr)
        first = jnp.logical_and(newly, jnp.logical_not(conv))
        row_iters = jnp.where(first, i + 1, row_iters)
        return new, agree_rows, i + 1, jnp.logical_or(conv, newly), row_iters

    init_rows = support_agreement(levels, support)
    final, agree_rows, iters_run, conv, row_iters = jax.lax.while_loop(
        cond,
        body,
        (
            levels,
            init_rows,
            jnp.int32(0),
            jnp.logical_not(row_dirty),  # empty support = pre-converged
            jnp.where(row_dirty, T, 0).astype(jnp.int32),
        ),
    )
    row_iters = jnp.where(conv, row_iters, iters_run)
    agreement = masked_level_agreement(final, valid_mask)
    return TieredAutoResult(final, iters_run, agreement, conv, row_iters)


# -- ragged paged dispatch (docs/SERVING.md, "Paged column memory") --------
#
# The ragged forward serves requests with DIFFERING patch counts (mixed
# resolutions/aspect ratios) in ONE dispatch: rows pack onto a flat,
# page-aligned token axis of T = n_pages x page_tokens positions instead
# of each padding to the worst row's [bucket, n_max] shape. Per-row
# structure is recovered in-graph from `n_patches` alone (page-aligned
# row starts by cumulative sum), and consensus attention becomes a
# row-WINDOWED gather: every token attends over its own row's window of
# W = pages(num_patches) x page_tokens positions, padded past the row's
# real length with hard-masked slots. W is the SAME static width in every
# ragged signature, so a row's attention layout — gather order, softmax
# axis length, masked tail — is identical whether the row dispatches
# alone or packed with others: the threshold-0 ragged dispatch is BITWISE
# the per-row lone dispatches it replaced (locked by
# tests/test_paged_columns.py; cross-route vs the dense engine the
# contract is the PR 4 scoping — same update ops, kernel-parity
# tolerance). Short rows are masked out of the witness per POSITION, not
# just per row: a pad slot never votes a bucket out of (or into) the
# early-exit loop.


class RaggedResult(NamedTuple):
    """One ragged dispatch's outcome (device arrays). `levels` is the
    FLAT [T, L, d] page-aligned state — callers slice row r's columns at
    [row_start[r], row_start[r] + n_patches[r]). Rows with n_patches 0
    are unused slots (masked everywhere, stamped converged)."""

    levels: jnp.ndarray         # [T, L, d]
    iters_run: jnp.ndarray      # int32 scalar
    row_converged: jnp.ndarray  # [R] bool
    row_iters: jnp.ndarray      # [R] int32


def ragged_row_layout(n_patches, page_tokens: int):
    """The in-graph row layout: page-aligned token starts from the patch
    counts alone. Returns (starts [R+1] int32 — starts[r] is row r's
    first flat token, starts[R] the used-token total; row_id [T] needs T,
    so callers derive it). The HOST packer (serve/batcher.py) computes
    the same layout with numpy — both sides derive from n_patches, so
    they can never disagree."""
    pages = (n_patches + page_tokens - 1) // page_tokens
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(pages).astype(jnp.int32)]
    ) * page_tokens


def _ragged_structure(n_patches, page_tokens: int, T: int):
    """(row_id [T], tok_off [T], tok_valid [T]) from the page-aligned
    layout. Tokens past the last used page clamp to the final row and
    read invalid (their offset lands past its patch count)."""
    R = n_patches.shape[0]
    starts = ragged_row_layout(n_patches, page_tokens)  # [R+1]
    t = jnp.arange(T, dtype=jnp.int32)
    row_id = jnp.sum(
        (t[:, None] >= starts[None, 1:]).astype(jnp.int32), axis=1
    )
    row_id = jnp.minimum(row_id, R - 1)
    tok_off = t - starts[row_id]
    tok_valid = tok_off < n_patches[row_id]
    return row_id, tok_off, tok_valid, starts


def ragged_consensus_attention(
    levels: jnp.ndarray,
    *,
    row_start: jnp.ndarray,
    row_len: jnp.ndarray,
    window: int,
    attend_self: bool = False,
) -> jnp.ndarray:
    """Row-windowed consensus attention over a flat [T, L, d] state:
    token t attends over the `window` positions starting at its row's
    flat offset, with slots past the row's real length hard-masked
    (max_neg — exactly zero attention after softmax) and the self slot
    soft-masked as the dense op does. row_start/row_len are PER TOKEN
    ([T] int32). Same q/k/v convention as ops/consensus.consensus_attention
    (raw q and v, L2-normalized k, d^-1/2 scale)."""
    T = levels.shape[0]
    d = levels.shape[-1]
    q = levels
    k = l2norm(levels, axis=-1)
    v = levels
    w = jnp.arange(window, dtype=jnp.int32)
    widx = row_start[:, None] + w[None, :]           # [T, W]
    wvalid = w[None, :] < row_len[:, None]           # [T, W]
    widx_c = jnp.clip(widx, 0, T - 1)
    kw = k[widx_c]                                   # [T, W, L, d]
    vw = v[widx_c]
    scale = d ** -0.5
    sim = jnp.einsum(
        "tld,twld->tlw", q, kw, preferred_element_type=jnp.float32
    )
    sim = sim * scale
    if not attend_self:
        self_slot = widx == jnp.arange(T, dtype=jnp.int32)[:, None]
        sim = jnp.where(self_slot[:, None, :], TOKEN_ATTEND_SELF_VALUE, sim)
    sim = jnp.where(wvalid[:, None, :], sim, max_neg_value(sim.dtype))
    attn = jax.nn.softmax(sim, axis=-1).astype(levels.dtype)
    out = jnp.einsum(
        "tlw,twld->tld", attn, vw, preferred_element_type=jnp.float32
    )
    return out.astype(levels.dtype)


def banded_ragged_consensus_attention(
    levels: jnp.ndarray,
    *,
    row_start: jnp.ndarray,
    row_len: jnp.ndarray,
    window: int,
    page_tokens: int,
    attend_self: bool = False,
) -> jnp.ndarray:
    """Block-banded consensus attention: the PAGE-granular form of
    ragged_consensus_attention. Rows occupy whole pages with page-aligned
    starts, so every page belongs to exactly one row and all page_tokens
    tokens in it share (row_start, row_len) — the k/v band can therefore
    be gathered once per PAGE (W/page_tokens pages) instead of once per
    token (W positions), shrinking the duplicated window working set from
    T*W to T*W/page_tokens column states. Masks (self slot, band
    validity) are computed from the same per-token (widx, wvalid)
    predicates as the windowed route, so at threshold 0 the output is
    BITWISE the windowed gather's (locked by tests/test_paged_columns.py
    and the --banded-ab gate)."""
    T = levels.shape[0]
    L = levels.shape[1]
    d = levels.shape[-1]
    pt = page_tokens
    if T % pt or window % pt:
        raise ValueError(
            f"banded consensus needs page-aligned shapes: T={T}, "
            f"window={window}, page_tokens={pt}"
        )
    P = T // pt
    Wp = window // pt
    q = levels.reshape(P, pt, L, d)
    k = l2norm(levels, axis=-1).reshape(P, pt, L, d)
    v = levels.reshape(P, pt, L, d)
    # Every token in a page shares its row's flat start (page-aligned
    # rows), so the band's first page is a per-page scalar.
    band_page0 = row_start[::pt] // pt                      # [P]
    wp = jnp.arange(Wp, dtype=jnp.int32)
    band = jnp.clip(band_page0[:, None] + wp[None, :], 0, P - 1)
    kb = k[band].reshape(P, Wp * pt, L, d)                  # [P, W, L, d]
    vb = v[band].reshape(P, Wp * pt, L, d)
    scale = d ** -0.5
    sim = jnp.einsum(
        "pqld,pwld->pqlw", q, kb, preferred_element_type=jnp.float32
    ).reshape(T, L, window)
    sim = sim * scale
    w = jnp.arange(window, dtype=jnp.int32)
    widx = row_start[:, None] + w[None, :]                  # [T, W]
    wvalid = w[None, :] < row_len[:, None]                  # [T, W]
    if not attend_self:
        self_slot = widx == jnp.arange(T, dtype=jnp.int32)[:, None]
        sim = jnp.where(self_slot[:, None, :], TOKEN_ATTEND_SELF_VALUE, sim)
    sim = jnp.where(wvalid[:, None, :], sim, max_neg_value(sim.dtype))
    attn = jax.nn.softmax(sim, axis=-1).astype(levels.dtype)
    out = jnp.einsum(
        "pqlw,pwld->pqld", attn.reshape(P, pt, L, window), vb,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(T, L, d).astype(levels.dtype)


def ragged_window_bytes(
    T: int, window: int, levels: int, dim: int, itemsize: int,
    page_tokens: int, attention: str = "windowed",
) -> int:
    """Peak duplicated k/v working-set bytes one consensus iteration
    materializes beyond the flat [T, L, d] state: the windowed gather
    copies W column states per TOKEN (k and v), the banded route W per
    PAGE — a page_tokens-fold reduction. This is the number the
    --banded-ab gate prices (serve_ragged.peak_window_bytes) and the
    bound that caps the largest admissible ragged signature per chip."""
    per_pos = 2 * levels * dim * itemsize  # k + v, one column state
    if attention == "windowed":
        return T * window * per_pos
    if attention in ("banded", "banded-pallas"):
        # The pallas kernel streams pages without materializing the band,
        # but its jnp fallback (and the interpret route) still build it —
        # price the banded working set for both.
        return (
            (T // page_tokens)
            * (window // page_tokens)
            * page_tokens
            * per_pos
        )
    raise ValueError(
        f"attention {attention!r}: 'windowed', 'banded', or 'banded-pallas'"
    )


def ragged_row_agreement(
    levels: jnp.ndarray, row_weight: jnp.ndarray, row_id: jnp.ndarray,
    n_patches: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row [R, L] consensus agreement from a flat [T, L, d] state —
    batch_agreement's reduction with the row mean taken by a masked
    segment sum, so a short row's PAD SLOTS never contribute to its mean
    direction (the per-position masking the ragged witness requires).
    row_weight is the [T, R] float one-hot of (row_id, tok_valid)."""
    x = levels.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    denom = jnp.maximum(n_patches.astype(jnp.float32), 1.0)
    mean = (
        jnp.einsum("tr,tld->rld", row_weight, xhat)
        / denom[:, None, None]
    )
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    cos = jnp.sum(xhat * mhat[row_id], axis=-1)  # [T, L]
    return jnp.einsum("tr,tl->rl", row_weight, cos) / denom[:, None]


def glom_forward_ragged(
    params,
    patches: jnp.ndarray,
    cfg: GlomConfig,
    *,
    n_patches: jnp.ndarray,
    page_tokens: int,
    route,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    quorum: float = 1.0,
    levels0: Optional[jnp.ndarray] = None,
    pool: Optional[jnp.ndarray] = None,
    page_idx: Optional[jnp.ndarray] = None,
    compute_dtype=None,
    use_pallas: bool = False,
    ragged_attention: str = "windowed",
) -> RaggedResult:
    """The ragged paged GLOM forward: one dispatch over a flat
    page-aligned token axis.

    patches: [T, patch_dim] host-patchified rows packed page-aligned in
    row order (T = total pages x page_tokens; the embed matmul runs
    in-graph so token values are bitwise the dense path's). n_patches:
    [R] per-row patch counts, 0 marking unused row slots. route: "auto"
    (tiered quorum exit, budget max_iters) or an int (fixed count).

    Warm state arrives ONE of two ways: `levels0` [T, L, d] flat (the
    host-carry form — continuation stragglers), or `pool` [N, page_tokens,
    L, d] + `page_idx` [T/page_tokens] int32 — the device-resident page
    pool with -1 marking cold pages, assembled in-graph by a page-index
    take so warm columns never cross the host boundary
    (serve/paged_columns.py). threshold=0.0 keeps the bitwise contract:
    no row ever converges, exactly max_iters updates run, and each row's
    state equals its lone ragged dispatch bit-for-bit.

    ragged_attention selects the consensus gather: "windowed" (the
    row-windowed per-token gather), "banded" (the page-blocked band —
    same values, W/page_tokens-fold smaller duplicated working set,
    bitwise the windowed route at threshold 0), or "banded-pallas" (the
    streaming kernel in kernels/banded_consensus.py — kernel-parity
    tolerance off the bitwise contract, like the fused dense route).
    """
    if cfg.local_consensus_radius > 0:
        raise ValueError(
            "ragged dispatch requires local_consensus_radius == 0 (the "
            "row window has no per-resolution 2D grid to build a radius "
            "mask from)"
        )
    if pool is not None and levels0 is not None:
        raise ValueError("pass levels0 OR pool+page_idx, not both")
    auto = route == "auto"
    if auto:
        T_budget = max_iters if max_iters is not None else cfg.default_iters
        _validate_auto_args(T_budget, min_iters, threshold)
    else:
        T_budget = int(route)
        if T_budget < 1:
            raise ValueError(f"route={route!r}: an int >= 1 or 'auto'")

    if use_pallas:
        from glom_tpu.kernels import fused_grouped_ffw

        ffw_fn = fused_grouped_ffw
    else:
        from glom_tpu.ops.ffw import grouped_ffw

        ffw_fn = grouped_ffw

    T = patches.shape[0]
    R = n_patches.shape[0]
    n_patches = n_patches.astype(jnp.int32)
    # The row window: full-resolution pages x page_tokens, the SAME
    # static width in every ragged signature (the bitwise anchor — see
    # the section comment above).
    window = min(
        T, ((cfg.num_patches + page_tokens - 1) // page_tokens) * page_tokens
    )

    # Identical cast discipline to _build_update_step: once, outside the
    # loop.
    if compute_dtype is not None:
        params = jax.tree_util.tree_map(
            lambda t: t.astype(compute_dtype), params
        )
        patches = patches.astype(compute_dtype)
        if exists(levels0):
            levels0 = levels0.astype(compute_dtype)

    row_id, tok_off, tok_valid, starts = _ragged_structure(
        n_patches, page_tokens, T
    )
    row_start_tok = starts[row_id]               # [T]
    row_len_tok = n_patches[row_id]              # [T]

    with jax.named_scope("patches_to_tokens"):
        tokens = patches @ params.token_embed.w + params.token_embed.b
    d = tokens.shape[-1]
    pos_flat = params.pos_emb[
        jnp.clip(tok_off, 0, params.pos_emb.shape[0] - 1)
    ]
    pos = pos_flat[None, :, None, :]             # [1, T, 1, d]
    bottom = tokens[None, :, None, :]            # [1, T, 1, d]

    init_flat = jnp.broadcast_to(
        params.init_levels[None], (T, cfg.levels, d)
    ).astype(tokens.dtype)
    if pool is not None:
        with jax.named_scope("page_take"):
            pages = pool[jnp.clip(page_idx, 0, pool.shape[0] - 1)]
            pages = jnp.where(
                (page_idx >= 0)[:, None, None, None],
                pages.astype(tokens.dtype),
                init_flat.reshape(
                    T // page_tokens, page_tokens, cfg.levels, d
                ),
            )
            levels = pages.reshape(T, cfg.levels, d)[None]
    elif exists(levels0):
        levels = levels0[None].astype(tokens.dtype)
    else:
        levels = init_flat[None]
    divisor = contribution_divisor(cfg.levels, jnp.float32)

    if ragged_attention == "banded-pallas":
        from glom_tpu.kernels import banded_ragged_consensus

        def consensus_fn(lv):
            return banded_ragged_consensus(
                lv[0],
                row_start=row_start_tok,
                row_len=row_len_tok,
                window=window,
                page_tokens=page_tokens,
                attend_self=cfg.consensus_self,
            )[None]
    elif ragged_attention == "banded":

        def consensus_fn(lv):
            return banded_ragged_consensus_attention(
                lv[0],
                row_start=row_start_tok,
                row_len=row_len_tok,
                window=window,
                page_tokens=page_tokens,
                attend_self=cfg.consensus_self,
            )[None]
    elif ragged_attention == "windowed":

        def consensus_fn(lv):
            return ragged_consensus_attention(
                lv[0],
                row_start=row_start_tok,
                row_len=row_len_tok,
                window=window,
                attend_self=cfg.consensus_self,
            )[None]
    else:
        raise ValueError(
            f"ragged_attention={ragged_attention!r}: 'windowed', 'banded' "
            "or 'banded-pallas'"
        )

    def step(lv):
        return update_step(
            params, lv, bottom, pos, divisor,
            consensus_fn=consensus_fn, ffw_fn=ffw_fn,
        )

    valid = n_patches > 0                        # [R]
    row_weight = (
        jnp.logical_and(
            row_id[:, None] == jnp.arange(R, dtype=jnp.int32)[None, :],
            tok_valid[:, None],
        )
    ).astype(jnp.float32)                        # [T, R]

    if not auto:
        final, _ = jax.lax.scan(
            lambda lv, _: (step(lv), None), levels, None, length=T_budget
        )
        return RaggedResult(
            final[0],
            jnp.int32(T_budget),
            jnp.ones((R,), bool),
            jnp.full((R,), T_budget, jnp.int32),
        )

    def row_agreement(lv):
        return ragged_row_agreement(lv[0], row_weight, row_id, n_patches)

    need = quorum_need(quorum, jnp.sum(valid.astype(jnp.float32)))
    thr = jnp.float32(threshold)

    def cond(carry):
        lv, prev_rows, i, conv, row_iters = carry
        n_conv = jnp.sum(jnp.logical_and(conv, valid).astype(jnp.int32))
        return jnp.logical_and(i < T_budget, n_conv < need)

    def body(carry):
        lv, prev_rows, i, conv, row_iters = carry
        new = step(lv)
        agree_rows = row_agreement(new)          # [R, L]
        delta = row_agreement_delta(agree_rows, prev_rows)
        newly = jnp.logical_and(i + 1 >= min_iters, delta < thr)
        first = jnp.logical_and(newly, jnp.logical_not(conv))
        row_iters = jnp.where(first, i + 1, row_iters)
        return new, agree_rows, i + 1, jnp.logical_or(conv, newly), row_iters

    init_rows = row_agreement(levels)
    final, _, iters_run, conv, row_iters = jax.lax.while_loop(
        cond,
        body,
        (
            levels,
            init_rows,
            jnp.int32(0),
            jnp.zeros((R,), bool),
            jnp.full((R,), T_budget, jnp.int32),
        ),
    )
    row_iters = jnp.where(conv, row_iters, iters_run)
    return RaggedResult(final[0], iters_run, conv, row_iters)
