"""Consensus-convergence early exit: stop iterating when the columns settle.

GLOM's forward is iterative settling — T is a budget, not a requirement.
The per-level consensus agreement the telemetry subsystem already computes
in-graph (telemetry/diagnostics.level_agreement, the "islands of agreement"
formation signal) doubles as a stopping witness: when one more column
update no longer moves any level's agreement by more than a threshold, the
columns have converged and further iterations are wasted serving latency.

`glom_forward_auto` is the fixed-`iters` forward (models/core.glom_forward)
with the `lax.scan` replaced by a `lax.while_loop`:

  * the loop body is the SAME `update_step` (same ops, same order, same
    dtype discipline), so threshold=0.0 — where the exit condition can
    never fire (the agreement delta is >= 0, the test is strict <) — runs
    exactly `max_iters` iterations and reproduces the fixed-`iters` output
    BITWISE (locked by tests/test_serve.py);
  * `max_iters` is STATIC: shapes stay fixed, the program compiles once per
    bucket signature, and a non-converging input is bounded — the while
    loop only ever exits EARLY, never runs long;
  * the witness is the max-over-levels absolute delta of the [L] agreement
    vector between consecutive iterations, computed on the state the body
    already holds (one extra [L] reduction per iteration — the same cost
    telemetry_level="full" pays per training step);
  * `valid_mask` restricts the witness to real requests: a serving batch
    padded to its bucket must not let the PAD rows (which converge
    instantly — a constant image collapses to one island) vote the batch
    out of the loop early, nor hold it in.

The trade against the scanned forward: a while loop cannot be unrolled or
pipelined as aggressively by XLA, and autodiff does not apply — this is an
INFERENCE form (glom_tpu/serve), not a training path.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from einops import rearrange

from glom_tpu.models.core import contribution_divisor, update_step
from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.ops.patch import image_to_tokens
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.helpers import exists


def batch_agreement(levels: jnp.ndarray) -> jnp.ndarray:
    """Per-image, per-level consensus agreement from a state [b, n, L, d]:
    mean over n of the cosine between each patch's level vector and that
    image's mean vector at the same level -> [b, L] float32. The batch
    mean of this is exactly diagnostics.level_agreement; serving keeps the
    batch axis so pad rows can be masked out of the stopping witness."""
    x = levels.astype(jnp.float32)
    eps = 1e-8
    xhat = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + eps)
    mean = jnp.mean(xhat, axis=1, keepdims=True)  # [b, 1, L, d]
    mhat = mean / (jnp.linalg.norm(mean, axis=-1, keepdims=True) + eps)
    return jnp.mean(jnp.sum(xhat * mhat, axis=-1), axis=1)  # [b, L]


def masked_level_agreement(
    levels: jnp.ndarray, valid_mask: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """[L] agreement over the VALID rows only (all rows when mask is None).
    With an all-true mask this equals diagnostics.level_agreement exactly
    (same reductions, grouped batch-last instead of batch-first)."""
    per_image = batch_agreement(levels)  # [b, L]
    if valid_mask is None:
        return jnp.mean(per_image, axis=0)
    w = valid_mask.astype(jnp.float32)[:, None]  # [b, 1]
    return jnp.sum(per_image * w, axis=0) / jnp.maximum(jnp.sum(w), 1.0)


def glom_forward_auto(
    params,
    img: jnp.ndarray,
    cfg: GlomConfig,
    *,
    max_iters: Optional[int] = None,
    threshold: float = 1e-3,
    min_iters: int = 1,
    levels: Optional[jnp.ndarray] = None,
    valid_mask: Optional[jnp.ndarray] = None,
    compute_dtype=None,
    use_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The early-exit GLOM forward: up to `max_iters` column updates,
    stopping once the agreement delta drops below `threshold`.

    Returns (final_levels [b, n, L, d], iters_run int32 scalar,
    agreement [L] float32 of the final state). `min_iters` floors the exit
    (at least that many updates always run); `threshold=0.0` disables the
    exit entirely — the strict `delta < threshold` test can then never
    pass and exactly `max_iters` updates run, bitwise-equal to
    glom_forward(iters=max_iters).

    use_pallas swaps the grouped-FFW for the fused Pallas kernel (which
    auto-falls back to the XLA form off-TPU); consensus stays the dense op
    — the serving engine compiles per bucket, and the reference-layout
    body keeps the exit witness identical across routes.
    """
    T = max_iters if max_iters is not None else cfg.default_iters
    if T < 1:
        raise ValueError(f"max_iters={T} must be >= 1")
    if not 1 <= min_iters <= T:
        raise ValueError(f"min_iters={min_iters} outside 1..{T}")
    if threshold < 0:
        raise ValueError(f"threshold={threshold} must be >= 0")

    if use_pallas:
        from glom_tpu.kernels import fused_grouped_ffw

        ffw_fn = fused_grouped_ffw
    else:
        from glom_tpu.ops.ffw import grouped_ffw

        ffw_fn = grouped_ffw

    local_mask = build_local_mask(cfg.num_patches_side, cfg.local_consensus_radius)
    consensus_fn = partial(
        consensus_attention,
        attend_self=cfg.consensus_self,
        local_mask=local_mask,
    )

    # Identical prologue to glom_forward: cast ONCE, outside the loop.
    if compute_dtype is not None:
        params = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
        img = img.astype(compute_dtype)
        if exists(levels):
            levels = levels.astype(compute_dtype)

    with jax.named_scope("image_to_tokens"):
        tokens = image_to_tokens(params.token_embed, img, cfg.patch_size)
    b, n, d = tokens.shape
    pos = rearrange(params.pos_emb, "n d -> 1 n 1 d")
    bottom = rearrange(tokens, "b n d -> b n 1 d")

    if not exists(levels):
        levels = jnp.broadcast_to(
            params.init_levels[None, None], (b, n, cfg.levels, d)
        ).astype(tokens.dtype)

    divisor = contribution_divisor(cfg.levels, jnp.float32)
    thr = jnp.float32(threshold)

    def cond(carry):
        _, _, i, done = carry
        return jnp.logical_and(i < T, jnp.logical_not(done))

    def body(carry):
        lv, prev_agree, i, _ = carry
        new = update_step(
            params, lv, bottom, pos, divisor,
            consensus_fn=consensus_fn, ffw_fn=ffw_fn,
        )
        agree = masked_level_agreement(new, valid_mask)  # [L] f32
        delta = jnp.max(jnp.abs(agree - prev_agree))
        done = jnp.logical_and(i + 1 >= min_iters, delta < thr)
        return new, agree, i + 1, done

    init_agree = masked_level_agreement(levels, valid_mask)
    final, agree, iters_run, _ = jax.lax.while_loop(
        cond, body, (levels, init_agree, jnp.int32(0), jnp.bool_(False))
    )
    return final, iters_run, agree
