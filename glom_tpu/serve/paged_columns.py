"""Device-resident paged column memory: the HBM page pool behind the warm
serving path and ragged admission.

PR 8's ColumnCache killed repeated convergence but kept the cached
`[n, L, d]` columns HOST-side: every warm frame re-uploaded its columns
over PCIe before the forward even started. Following *Ragged Paged
Attention* (PAPERS.md) — pages as the residency unit, a page table as the
indirection — this module keeps warm column state WHERE IT IS USED:

  * ONE preallocated device buffer of `[n_pages, page_tokens, L, d]` per
    engine (the pool), sized by `ServeConfig.page_pool_pages` and priced
    in the same analytic live-bytes form as `column_state_bytes`;
  * a host-side PAGE TABLE mapping `(session_id, block ordinal)` to page
    indices — allocation hands out free pages (no contiguity needed: the
    dispatch gathers by index), free returns them, and `defrag()`
    compacts allocated pages toward low indices (a device-to-device
    gather/scatter, stamped `page_defrag`) so long-lived pools keep
    gather locality;
  * write-back on resolve copies converged columns DEVICE-TO-DEVICE into
    the session's owned pages (`write_back`: a memoized jitted scatter —
    the columns never visit the host), and the warm dispatch assembles
    `levels0` IN-GRAPH via a page-index take (engine.py's paged
    signatures) — zero host<->device levels0 transfer on the warm path,
    the number `bench_serve.py --ragged` asserts via the engine's
    transfer counters;
  * pages are PINNED while a dispatch reads them (`pin`/`unpin`): the
    cache's eviction policy skips pinned blocks, so an in-flight gather
    can never read pages a concurrent eviction re-issued. Engine death
    force-frees (the dispatch that observed the death demotes its rows
    to cold on requeue — serve/batcher.py).

The pool buffer defaults to copy-on-write (a write-back builds the next
buffer functionally and swaps the reference under the lock): in-flight
dispatches keep reading the buffer they snapshotted, so the scatter is
never donated. That is correct but doubles pool traffic — every
write-back copies the whole pool to change a few pages. With
`ServeConfig.pool_aliasing` the pool promotes write-backs to DONATED
in-graph updates behind an explicit serialization seam: dispatches pin
the buffer they read (`acquire_read`/`release_read` — the engine wraps
every pool dispatch in the pair), a write-back donates the buffer ONLY
when no read pin is live (bumping the pool EPOCH — the donated buffer
is dead, the epoch names the new one), and falls back to CoW LOUDLY
(stamped `alias_fallback`, counted) when a snapshot is pinned. Chain
compaction and defrag stay CoW (their src/dst page ranges can overlap
— an in-place scatter would read half-moved state). Refcounted shared
bases and delta chains are unaffected: the page TABLE never aliases,
only the buffer update does. Aliasing off is byte-for-byte the old CoW
behavior. XLA reuses the dropped buffer's HBM either way; under CoW the
transient double-residency window is one write-back wide, under
aliasing it is gone.

Accounting: every alloc/free/defrag is a stamped "serve" event
(`page_alloc`/`page_free`/`page_defrag`, docs/OBSERVABILITY.md) and
`record()` rolls pages/bytes/churn into the batcher summary in the same
live-bytes vocabulary the column cache uses.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np


def resolve_page_tokens(cfg, scfg) -> int:
    """The page granularity in patch tokens. An explicit
    `ServeConfig.page_tokens` must tile the full-resolution row (the
    bucket route maps `[bucket, num_patches]` onto whole pages); 0
    resolves to the largest divisor of `num_patches` that is at most
    min(64, num_patches // 4) — at least FOUR pages per full-resolution
    row (coarser and a half-resolution row pays a whole-row page, which
    is the pad tax back again), capped at 64 tokens so the page-index
    take stays coarse-grained on big models (flagship 256 patches ->
    64-token pages)."""
    n = cfg.num_patches
    if scfg.page_tokens > 0:
        if n % scfg.page_tokens != 0:
            raise ValueError(
                f"page_tokens {scfg.page_tokens} does not divide "
                f"num_patches {n} (pages must tile the full-resolution row)"
            )
        return scfg.page_tokens
    for cand in range(max(1, min(64, n // 4)), 0, -1):
        if n % cand == 0:
            return cand
    return n  # pragma: no cover — cand=1 always divides


def pages_for_tokens(n_tokens: int, page_tokens: int) -> int:
    """ceil(n_tokens / page_tokens): pages one row's columns occupy."""
    if n_tokens < 1:
        raise ValueError(f"n_tokens {n_tokens} must be >= 1")
    return -(-n_tokens // page_tokens)


def page_state_bytes(cfg, scfg, page_tokens: Optional[int] = None) -> int:
    """The live-bytes price of ONE pool page — `page_tokens x levels x
    dim` in the serving dtype, the per-page unit `column_state_bytes`
    decomposes into (docs/SERVING.md, "Paged column memory")."""
    pt = page_tokens if page_tokens is not None else resolve_page_tokens(cfg, scfg)
    itemsize = 2 if scfg.compute_dtype == "bfloat16" else 4
    return pt * cfg.levels * cfg.dim * itemsize


class _Block:
    """One session's page-table entry: the ordered page indices holding
    its column state (block ordinal k covers tokens [k*pt, (k+1)*pt))."""

    __slots__ = ("pages", "n_tokens", "pins")

    def __init__(self, pages: List[int], n_tokens: int):
        self.pages = pages
        self.n_tokens = n_tokens
        self.pins = 0


class _BaseBlock:
    """A DELTA-mode base: whole-row page set, REFCOUNTED so sessions with
    content-identical bases (hash-matched at write-back) alias the same
    read-only pool pages — two cameras on one scene pay for one base.
    Pages free only when the last referencing session drops."""

    __slots__ = ("pages", "n_tokens", "refs", "hkey")

    def __init__(self, pages: List[int], n_tokens: int, hkey=None):
        self.pages = pages
        self.n_tokens = n_tokens
        self.refs = 1
        self.hkey = hkey


class _DeltaBlock:
    """A DELTA-mode session entry: a (possibly shared) base plus a chain
    of frame-to-frame deltas, each a {block ordinal -> page index} map of
    ONLY the pages whose column residual exceeded `delta_page_atol`. The
    effective page map is base overridden by the chain newest-last —
    base+Σdeltas resolved to plain page indices, so reconstruction rides
    the SAME in-graph page-index take every paged dispatch already uses."""

    __slots__ = ("base", "deltas", "n_tokens", "pins")

    def __init__(self, base: _BaseBlock, n_tokens: int):
        self.base = base
        self.deltas: List[Dict[int, int]] = []
        self.n_tokens = n_tokens
        self.pins = 0

    def effective(self) -> List[int]:
        pages = list(self.base.pages)
        for d in self.deltas:
            for ordinal, page in d.items():
                pages[ordinal] = page
        return pages

    def delta_pages(self) -> List[int]:
        return [p for d in self.deltas for p in d.values()]


class PagedColumnPool:
    """Fixed-size device page pool + host page table for one engine.

    `mesh`/`pool_sharding` route the buffer through a NamedSharding on
    the page axis (the sharded engines' pool — parallel/serve_mesh.py
    gathers it with a registered all_gather); None keeps the
    single-device buffer. The injectable `writer` delivers the stamped
    page events through the usual writer-else-flight path."""

    def __init__(
        self,
        cfg,
        scfg,
        *,
        writer=None,
        name: str = "engine0",
        pool_sharding=None,
    ):
        import jax.numpy as jnp

        if scfg.page_pool_pages < 1:
            raise ValueError(
                f"page_pool_pages {scfg.page_pool_pages} must be >= 1 to "
                "build a pool (0 disables paged columns — resolve first)"
            )
        self.cfg = cfg
        self.scfg = scfg
        self.name = name
        self.writer = writer
        self.page_tokens = resolve_page_tokens(cfg, scfg)
        self.n_pages = int(scfg.page_pool_pages)
        self.page_bytes = page_state_bytes(cfg, scfg, self.page_tokens)
        self.pool_bytes = self.n_pages * self.page_bytes
        self._dtype = (
            jnp.bfloat16 if scfg.compute_dtype == "bfloat16" else jnp.float32
        )
        self._lock = threading.Lock()
        self._table: Dict[str, _Block] = {}
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self._scatter_fns: Dict = {}
        self._gather_fns: Dict = {}
        self.n_allocs = 0
        self.n_frees = 0
        self.n_alloc_fails = 0
        self.n_writebacks = 0
        self.n_defrag_moves = 0
        self._pages_peak = 0
        # Delta streaming (ServeConfig.delta_streaming, docs/SERVING.md
        # "Delta streaming"): sessions written through write_back_stream
        # hold a refcounted BASE plus a chain of sparse deltas instead of
        # a whole-row block. The atol decides what counts as a changed
        # page (0.0 = any changed BIT — bitcast-compared, so -0.0 vs 0.0
        # still stores); the chain compacts at the cap; content-identical
        # bases alias via the hash index.
        self.delta = bool(getattr(scfg, "delta_streaming", False))
        self.delta_page_atol = float(getattr(scfg, "delta_page_atol", 0.0))
        self.delta_chain_cap = int(getattr(scfg, "delta_chain_cap", 4))
        self._share = bool(getattr(scfg, "delta_base_share", True))
        self._hash_index: Dict[str, _BaseBlock] = {}
        self._residual_fns: Dict = {}
        self._delta_scatter_fns: Dict = {}
        self._compact_fns: Dict = {}
        self.n_delta_writes = 0
        self.n_delta_pages = 0
        self.n_delta_empty = 0
        self.n_compactions = 0
        self.n_compact_deferred = 0
        self.n_base_shares = 0
        self.n_superseded = 0
        # In-place aliasing (ServeConfig.pool_aliasing, module
        # docstring): donated write-backs gated by the read-pin count;
        # the epoch counts buffer identities (every donated update kills
        # the previous buffer). Bytes-moved counters price the A/B in
        # the analytic live-bytes form — a CoW write copies the whole
        # pool to change a few pages, an aliased write moves only the
        # pages written.
        self.aliasing = bool(getattr(scfg, "pool_aliasing", False))
        self._epoch = 0
        self._read_pins = 0
        self.n_alias_writes = 0
        self.n_alias_fallbacks = 0
        self.alias_bytes_moved = 0
        self.cow_bytes_moved = 0
        # THE preallocated buffer: pages x page_tokens x L x d, zeros.
        # One allocation up front — warm traffic never grows it.
        buf = jnp.zeros(
            (self.n_pages, self.page_tokens, cfg.levels, cfg.dim),
            self._dtype,
        )
        if pool_sharding is not None:
            import jax

            buf = jax.device_put(buf, pool_sharding)
        self._buffer = buf
        self._pool_sharding = pool_sharding

    # -- the page table ----------------------------------------------------

    def buffer(self):
        """The current pool buffer (snapshot for one dispatch). The
        reference swaps copy-on-write under the lock; pinned pages stay
        valid in every later buffer, so a dispatch built from (buffer,
        pinned indices) reads a consistent state. NOT safe as a dispatch
        handle under aliasing — a donated write-back invalidates
        unpinned snapshots; the dispatch path takes `acquire_read()`
        instead (glom-lint's donation-safety flags the bare form)."""
        with self._lock:
            return self._buffer

    def acquire_read(self):
        """Pin the CURRENT buffer for one dispatch and return it. While
        any read pin is live, write-backs cannot donate (they fall back
        to CoW, stamped `alias_fallback`), so the returned reference
        stays valid for the dispatch's whole lifetime — snapshot through
        block_until_ready. Pair with `release_read()` in a finally.
        With aliasing off this is `buffer()` plus a free counter."""
        with self._lock:
            if self._buffer is None:
                raise RuntimeError(
                    f"pool {self.name!r} released: dispatch against a "
                    "drained replica is a fleet-bookkeeping bug"
                )
            self._read_pins += 1
            return self._buffer

    def release_read(self) -> None:
        """Drop one dispatch's read pin (the `acquire_read` pair)."""
        with self._lock:
            if self._read_pins <= 0:
                raise RuntimeError(
                    "release_read without a matching acquire_read"
                )
            self._read_pins -= 1

    def read_pins(self) -> int:
        with self._lock:
            return self._read_pins

    def epoch(self) -> int:
        """Buffer-identity counter: bumps on every DONATED write-back
        (the previous buffer is dead). CoW swaps keep the epoch — the
        old snapshot stays readable."""
        with self._lock:
            return self._epoch

    def pages_used(self) -> int:
        with self._lock:
            return self.n_pages - len(self._free)

    def bytes_in_use(self) -> int:
        return self.pages_used() * self.page_bytes

    def holds(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._table

    def lookup(self, session_id: str, *, pin: bool = False):
        """(pages, n_tokens) for the session, or None. pin=True takes a
        read pin (the dispatch path): the block survives eviction until
        the matching unpin — cache eviction skips pinned blocks."""
        with self._lock:
            blk = self._table.get(session_id)
            if blk is None:
                return None
            if pin:
                blk.pins += 1
            if isinstance(blk, _DeltaBlock):
                # The EFFECTIVE map: base overridden by the delta chain
                # newest-last — base+Σdeltas as plain page indices, ready
                # for the same in-graph page-index take as any warm
                # dispatch (zero levels0 H2D, the PR 11 contract).
                return blk.effective(), blk.n_tokens
            return list(blk.pages), blk.n_tokens

    def unpin(self, session_id: str) -> None:
        with self._lock:
            blk = self._table.get(session_id)
            if blk is not None and blk.pins > 0:
                blk.pins -= 1

    def is_pinned(self, session_id: str) -> bool:
        with self._lock:
            blk = self._table.get(session_id)
            return blk is not None and blk.pins > 0

    def alloc(self, session_id: str, n_tokens: int) -> Optional[List[int]]:
        """Own `ceil(n_tokens / page_tokens)` pages under the session
        key. An existing block of the right size is reused (the steady
        warm path — a stream's frames share a resolution); a resized
        session frees and re-allocates. None when the pool lacks free
        pages (the CALLER evicts — residency policy lives in the cache,
        mechanism here)."""
        need = pages_for_tokens(n_tokens, self.page_tokens)
        events = []
        with self._lock:
            blk = self._table.get(session_id)
            if isinstance(blk, _DeltaBlock):
                raise ValueError(
                    f"session {session_id!r} holds a delta-chain block; "
                    "whole-state alloc() does not compose with "
                    "write_back_stream on one key"
                )
            if blk is not None:
                if len(blk.pages) == need:
                    blk.n_tokens = n_tokens
                    return list(blk.pages)
                events.append(self._free_locked(session_id, blk, "resize"))
            if len(self._free) < need:
                self.n_alloc_fails += 1
                self._flush(events)
                return None
            pages = [self._free.pop() for _ in range(need)]
            self._table[session_id] = _Block(pages, n_tokens)
            self.n_allocs += 1
            used = self.n_pages - len(self._free)
            self._pages_peak = max(self._pages_peak, used)
            events.append(
                {
                    "event": "page_alloc",
                    "session": session_id,
                    "n_pages": need,
                    "n_tokens": n_tokens,
                    "pages_used": used,
                    "pages_total": self.n_pages,
                    "bytes_in_use": used * self.page_bytes,
                }
            )
        self._flush(events)
        return list(pages)

    def free(self, session_id: str, *, reason: str = "evict") -> int:
        """Return the session's pages to the free list (eviction, TTL
        expiry, engine-death invalidation). Returns pages freed (0 when
        absent). Force-frees pinned blocks too — the only force callers
        are death/invalidation paths whose in-flight readers demote to
        cold on requeue."""
        with self._lock:
            blk = self._table.get(session_id)
            if blk is None:
                return 0
            ev = self._free_locked(session_id, blk, reason)
            n = ev["n_pages"]
        self._flush([ev])
        return n

    def free_all(self, *, reason: str = "engine-death") -> int:
        """Drop EVERY block — the engine just died; its pool state is
        unreachable warmth. One stamped page_free with the totals."""
        with self._lock:
            n = self.n_pages - len(self._free)
            sessions = len(self._table)
            if not sessions:
                return 0
            self._table.clear()
            self._hash_index.clear()
            self._free = list(range(self.n_pages - 1, -1, -1))
            self.n_frees += sessions
            ev = {
                "event": "page_free",
                "reason": reason,
                "n_sessions": sessions,
                "n_pages": n,
                "pages_used": 0,
                "bytes_in_use": 0,
            }
        self._flush([ev])
        return n

    def _free_locked(self, session_id: str, blk, reason: str) -> dict:
        # Caller holds the lock. A delta block frees its chain pages and
        # DECREFS its base — the base's pages return to the free list
        # only when the last aliasing session drops (refcount 0), which
        # is exactly what "two cameras pay for one base" requires on the
        # way OUT too.
        self._table.pop(session_id, None)
        if isinstance(blk, _DeltaBlock):
            freed = blk.delta_pages()
            blk.base.refs -= 1
            if blk.base.refs == 0:
                freed = freed + blk.base.pages
                if blk.base.hkey is not None:
                    stored = self._hash_index.get(blk.base.hkey)
                    if stored is blk.base:
                        del self._hash_index[blk.base.hkey]
        else:
            freed = blk.pages
        self._free.extend(reversed(freed))
        self.n_frees += 1
        used = self.n_pages - len(self._free)
        return {
            "event": "page_free",
            "session": session_id,
            "reason": reason,
            "n_pages": len(freed),
            "pages_used": used,
            "bytes_in_use": used * self.page_bytes,
        }

    # -- device-side data movement ----------------------------------------

    def _donate_jit_kw(self, donate: bool) -> dict:
        """donate_argnums for the pool arg, TPU only — CPU jit ignores
        donation (with a warning), so off-TPU the "aliased" write is the
        same functional scatter and only the accounting differs. The
        seam logic (pins, epoch, fallback) is platform-independent."""
        if not donate:
            return {}
        import jax

        if jax.devices()[0].platform != "tpu":
            return {}
        return {"donate_argnums": (0,)}

    def _writeback_fn(self, k: int, n: int, *, donate: bool = False):
        """Memoized jitted scatter for a (pages, tokens) shape class:
        pad the row's [n, L, d] columns to whole pages and set them at
        the block's indices. Functional update — the result is the NEXT
        pool buffer. donate=True is the aliasing seam's in-place
        variant: the input pool buffer is donated, so the scatter
        updates the pages in place instead of copying the pool (see
        module docstring; only `_scatter_locked` may call it)."""
        key = (k, n, bool(donate))
        if key not in self._scatter_fns:
            import jax
            import jax.numpy as jnp

            pt = self.page_tokens
            L, d = self.cfg.levels, self.cfg.dim
            dtype = self._dtype

            def fn(pool, idx, row):
                flat = jnp.pad(
                    row.astype(dtype), ((0, k * pt - n), (0, 0), (0, 0))
                )
                return pool.at[idx].set(flat.reshape(k, pt, L, d))

            self._scatter_fns[key] = jax.jit(
                fn, **self._donate_jit_kw(donate)
            )
        return self._scatter_fns[key]

    def _scatter_locked(
        self,
        make_fn,
        args,
        *,
        pages_written: int,
        session_id: Optional[str],
        events: List[dict],
    ) -> None:
        """The ONE write seam (caller holds the lock): route a buffer
        update through aliasing when enabled AND no dispatch holds a
        read pin — the donated scatter kills the previous buffer, so
        the epoch bumps and `page_alias` stamps what moved. Any live
        pin forces the CoW fallback LOUDLY (`alias_fallback` + counter):
        correct, just back to paying the whole-pool copy. make_fn(donate)
        returns the memoized jitted scatter for that variant."""
        if self.aliasing and self._read_pins == 0:
            self._buffer = make_fn(True)(self._buffer, *args)
            self._epoch += 1
            self.n_alias_writes += 1
            self.alias_bytes_moved += pages_written * self.page_bytes
            events.append(
                {
                    "event": "page_alias",
                    "session": session_id,
                    "n_pages": pages_written,
                    "epoch": self._epoch,
                    "bytes_moved": pages_written * self.page_bytes,
                }
            )
        else:
            self._buffer = make_fn(False)(self._buffer, *args)
            self.cow_bytes_moved += self.pool_bytes
            if self.aliasing:
                self.n_alias_fallbacks += 1
                events.append(
                    {
                        "event": "alias_fallback",
                        "session": session_id,
                        "n_pages": pages_written,
                        "read_pins": self._read_pins,
                        "bytes_moved": self.pool_bytes,
                    }
                )

    def write_back(self, session_id: str, levels_row, n_tokens: int) -> bool:
        """Copy one resolved row's converged columns device-to-device
        into the session's pages (allocating on first write). levels_row
        is the DEVICE [n_tokens, L, d] slice of the dispatch output — it
        never visits the host. False when allocation failed (pool full:
        the cache's eviction pressure path frees and retries)."""
        pages = self.alloc(session_id, n_tokens)
        if pages is None:
            return False
        import jax.numpy as jnp

        k = len(pages)
        idx = jnp.asarray(np.asarray(pages, np.int32))
        events: List[dict] = []
        with self._lock:
            # The scatter runs under the lock: buffer swaps serialize
            # (two concurrent write-backs must not both extend the same
            # parent buffer and drop one update on the swap), and the
            # read-pin check that gates donation is atomic with the
            # update itself.
            self._scatter_locked(
                lambda donate: self._writeback_fn(
                    k, n_tokens, donate=donate
                ),
                (idx, levels_row),
                pages_written=k,
                session_id=session_id,
                events=events,
            )
            self.n_writebacks += 1
        self._flush(events)
        return True

    # -- delta streaming (docs/SERVING.md, "Delta streaming") --------------

    def _alloc_raw_locked(self, need: int) -> Optional[List[int]]:
        """Pop `need` free pages (caller holds the lock), or None."""
        if len(self._free) < need:
            self.n_alloc_fails += 1
            return None
        return [self._free.pop() for _ in range(need)]

    def _idx(self, pages) -> "object":
        import jax.numpy as jnp

        return jnp.asarray(np.asarray(pages, np.int32))

    def _residual_fn(self, k: int, n: int):
        """Memoized per-page residual probe for a (pages, tokens) shape
        class: compare one row's new [n, L, d] columns against its
        current effective pages and return ([k] any-bit-differs bool,
        [k] max-abs f32) — the host picks by atol (0.0 reads the BITCAST
        channel, so the stored/skipped decision is literally bitwise)."""
        key = (k, n)
        if key not in self._residual_fns:
            import jax
            import jax.numpy as jnp

            pt = self.page_tokens
            dtype = self._dtype
            int_t = jnp.int16 if dtype == jnp.bfloat16 else jnp.int32

            def fn(pool, eff_idx, row):
                flat = jnp.pad(
                    row.astype(dtype), ((0, k * pt - n), (0, 0), (0, 0))
                ).reshape(k, pt, *row.shape[1:])
                cur = pool[eff_idx]
                bits = jnp.any(
                    jax.lax.bitcast_convert_type(cur, int_t)
                    != jax.lax.bitcast_convert_type(flat, int_t),
                    axis=(1, 2, 3),
                )
                diff = jnp.max(
                    jnp.abs(
                        cur.astype(jnp.float32) - flat.astype(jnp.float32)
                    ),
                    axis=(1, 2, 3),
                )
                return bits, diff

            self._residual_fns[key] = jax.jit(fn)
        return self._residual_fns[key]

    def _delta_scatter_fn(self, kc: int, k: int, n: int, *, donate: bool = False):
        """Memoized scatter of `kc` CHANGED pages out of a row's `k`:
        (pool, dst_idx [kc], row [n, L, d], ordinals [kc]) -> next pool
        buffer (functional by default; donate=True is the aliasing
        seam's in-place variant — only `_scatter_locked` may call it).
        Delta pages scatter to FRESH pool pages, so the donated update
        never overwrites a page any effective map still resolves to."""
        key = (kc, k, n, bool(donate))
        if key not in self._delta_scatter_fns:
            import jax
            import jax.numpy as jnp

            pt = self.page_tokens
            dtype = self._dtype

            def fn(pool, dst_idx, row, ordinals):
                flat = jnp.pad(
                    row.astype(dtype), ((0, k * pt - n), (0, 0), (0, 0))
                ).reshape(k, pt, *row.shape[1:])
                return pool.at[dst_idx].set(flat[ordinals])

            self._delta_scatter_fns[key] = jax.jit(
                fn, **self._donate_jit_kw(donate)
            )
        return self._delta_scatter_fns[key]

    def _copy_pages_fn(self, k: int):
        """Memoized device-to-device page copy: (pool, src_idx [k],
        dst_idx [k]) -> next buffer with dst pages holding src content
        (reads the PRE-move buffer, so src/dst never alias mid-copy)."""
        if k not in self._compact_fns:
            import jax

            def fn(pool, src_idx, dst_idx):
                return pool.at[dst_idx].set(pool[src_idx])

            self._compact_fns[k] = jax.jit(fn)
        return self._compact_fns[k]

    def delta_chain_len(self, session_id: str) -> Optional[int]:
        with self._lock:
            blk = self._table.get(session_id)
            if not isinstance(blk, _DeltaBlock):
                return None
            return len(blk.deltas)

    def base_refs(self, session_id: str) -> Optional[int]:
        with self._lock:
            blk = self._table.get(session_id)
            if not isinstance(blk, _DeltaBlock):
                return None
            return blk.base.refs

    def _compact_locked(self, session_id: str, blk: _DeltaBlock, events) -> bool:
        """Fold base+Σdeltas into ONE base, device-to-device, under the
        pool's pin/conservation rules: a PINNED session defers (an
        in-flight dispatch snapshotted its chain's page indices — freeing
        them would let a re-allocation rewrite what that snapshot's NEXT
        buffer read resolves to); a sole-owner base compacts IN PLACE
        (only overridden ordinals copy); a SHARED base copies on write
        into fresh pages so the aliasing sessions keep theirs bit-for-bit.
        Returns True when the chain actually folded."""
        if blk.pins > 0:
            self.n_compact_deferred += 1
            return False
        overridden = sorted({o for d in blk.deltas for o in d.keys()})
        eff = blk.effective()
        chain_pages = blk.delta_pages()
        if blk.base.refs == 1:
            # In place: copy each overridden ordinal's newest page into
            # the base's page; unchanged ordinals already hold the base.
            if overridden:
                src = [eff[o] for o in overridden]
                dst = [blk.base.pages[o] for o in overridden]
                fn = self._copy_pages_fn(len(overridden))
                self._buffer = fn(
                    self._buffer, self._idx(src), self._idx(dst)
                )
            if blk.base.hkey is not None:
                # Content changed: the registered hash no longer names
                # these pages — de-index so no future session aliases a
                # stale fingerprint.
                stored = self._hash_index.get(blk.base.hkey)
                if stored is blk.base:
                    del self._hash_index[blk.base.hkey]
                blk.base.hkey = None
        else:
            fresh = self._alloc_raw_locked(len(blk.base.pages))
            if fresh is None:
                # Pool too tight to copy-on-write a shared base: keep the
                # over-cap chain (correct, just unfolded) and let
                # eviction pressure free room first.
                self.n_compact_deferred += 1
                return False
            fn = self._copy_pages_fn(len(eff))
            self._buffer = fn(self._buffer, self._idx(eff), self._idx(fresh))
            blk.base.refs -= 1
            blk.base = _BaseBlock(fresh, blk.n_tokens, hkey=None)
        blk.deltas = []
        if chain_pages:
            self._free.extend(reversed(chain_pages))
            used = self.n_pages - len(self._free)
            events.append(
                {
                    "event": "page_free",
                    "session": session_id,
                    "reason": "compact",
                    "n_pages": len(chain_pages),
                    "pages_used": used,
                    "bytes_in_use": used * self.page_bytes,
                }
            )
        self.n_compactions += 1
        return True

    def write_back_stream(
        self,
        session_id: str,
        levels_row,
        n_tokens: int,
        *,
        content_hash: Optional[str] = None,
    ) -> Optional[dict]:
        """The DELTA-mode write-back: first store lays down (or aliases)
        a BASE; every later store probes the row's per-page residual
        against the session's effective state and appends a delta holding
        ONLY the pages past `delta_page_atol` (atol 0.0 = any changed
        bit). The chain folds base <- base+Σdeltas at `delta_chain_cap`.
        `content_hash` (the batcher's hash of the exact row bytes) keys
        cross-stream base sharing. Returns an info dict for the cache's
        stamped cache_delta/cache_compact/cache_share events, or None
        when the pool lacks pages (the cache evicts and retries)."""
        need = pages_for_tokens(n_tokens, self.page_tokens)
        events: List[dict] = []
        info: Optional[dict] = None
        with self._lock:
            blk = self._table.get(session_id)
            if blk is not None and not isinstance(blk, _DeltaBlock):
                events.append(
                    self._free_locked(session_id, blk, "delta-convert")
                )
                blk = None
            if blk is not None and blk.n_tokens != n_tokens:
                events.append(self._free_locked(session_id, blk, "resize"))
                blk = None
            if blk is None:
                shared = None
                if content_hash is not None and self._share:
                    cand = self._hash_index.get(content_hash)
                    if cand is not None and cand.n_tokens == n_tokens:
                        shared = cand
                if shared is not None:
                    shared.refs += 1
                    self._table[session_id] = _DeltaBlock(shared, n_tokens)
                    self.n_base_shares += 1
                    info = {
                        "kind": "share",
                        "pages_written": 0,
                        "chain_len": 0,
                        "base_refs": shared.refs,
                    }
                else:
                    pages = self._alloc_raw_locked(need)
                    if pages is None:
                        self._flush(events)
                        return None
                    self._scatter_locked(
                        lambda donate: self._writeback_fn(
                            need, n_tokens, donate=donate
                        ),
                        (self._idx(pages), levels_row),
                        pages_written=need,
                        session_id=session_id,
                        events=events,
                    )
                    self.n_writebacks += 1
                    base = _BaseBlock(pages, n_tokens, hkey=content_hash)
                    if content_hash is not None and self._share:
                        self._hash_index[content_hash] = base
                    self._table[session_id] = _DeltaBlock(base, n_tokens)
                    self.n_allocs += 1
                    used = self.n_pages - len(self._free)
                    self._pages_peak = max(self._pages_peak, used)
                    events.append(
                        {
                            "event": "page_alloc",
                            "session": session_id,
                            "n_pages": need,
                            "n_tokens": n_tokens,
                            "delta_base": True,
                            "pages_used": used,
                            "pages_total": self.n_pages,
                            "bytes_in_use": used * self.page_bytes,
                        }
                    )
                    info = {
                        "kind": "base",
                        "pages_written": need,
                        "chain_len": 0,
                        "base_refs": 1,
                    }
            else:
                eff = blk.effective()
                probe = self._residual_fn(need, n_tokens)
                bits, diff = probe(self._buffer, self._idx(eff), levels_row)
                if self.delta_page_atol <= 0.0:
                    changed_mask = np.asarray(bits)
                else:
                    changed_mask = np.asarray(diff) > self.delta_page_atol
                ordinals = [int(o) for o in np.nonzero(changed_mask)[0]]
                if not ordinals:
                    self.n_delta_empty += 1
                    info = {
                        "kind": "delta",
                        "pages_written": 0,
                        "chain_len": len(blk.deltas),
                        "empty": True,
                    }
                else:
                    pages = self._alloc_raw_locked(len(ordinals))
                    if pages is None:
                        self._flush(events)
                        return None
                    self._scatter_locked(
                        lambda donate: self._delta_scatter_fn(
                            len(ordinals), need, n_tokens, donate=donate
                        ),
                        (
                            self._idx(pages),
                            levels_row,
                            self._idx(ordinals),
                        ),
                        pages_written=len(ordinals),
                        session_id=session_id,
                        events=events,
                    )
                    blk.deltas.append(dict(zip(ordinals, pages)))
                    self.n_delta_writes += 1
                    self.n_delta_pages += len(ordinals)
                    self.n_writebacks += 1
                    # Prune SUPERSEDED chain pages (unpinned blocks
                    # only): an ordinal overridden by a newer delta is
                    # never read again — the effective map takes the
                    # newest — so its page returns to the pool NOW
                    # instead of waiting for the cap compaction. This is
                    # what keeps a stream that keeps perturbing the same
                    # region at ~constant pages. Pinned blocks defer: an
                    # in-flight dispatch snapshotted those indices.
                    if blk.pins == 0 and len(blk.deltas) > 1:
                        covered = set(blk.deltas[-1].keys())
                        kept = [blk.deltas[-1]]
                        superseded: List[int] = []
                        for d in reversed(blk.deltas[:-1]):
                            for o in [o for o in d if o in covered]:
                                superseded.append(d.pop(o))
                            if d:
                                covered |= set(d.keys())
                                kept.append(d)
                        kept.reverse()
                        blk.deltas = kept
                        if superseded:
                            self._free.extend(reversed(superseded))
                            self.n_superseded += len(superseded)
                            used = self.n_pages - len(self._free)
                            events.append(
                                {
                                    "event": "page_free",
                                    "session": session_id,
                                    "reason": "superseded",
                                    "n_pages": len(superseded),
                                    "pages_used": used,
                                    "bytes_in_use": used * self.page_bytes,
                                }
                            )
                    used = self.n_pages - len(self._free)
                    self._pages_peak = max(self._pages_peak, used)
                    events.append(
                        {
                            "event": "page_alloc",
                            "session": session_id,
                            "n_pages": len(ordinals),
                            "n_tokens": n_tokens,
                            "delta": True,
                            "chain_len": len(blk.deltas),
                            "pages_used": used,
                            "pages_total": self.n_pages,
                            "bytes_in_use": used * self.page_bytes,
                        }
                    )
                    info = {
                        "kind": "delta",
                        "pages_written": len(ordinals),
                        "chain_len": len(blk.deltas),
                    }
                    if len(blk.deltas) >= self.delta_chain_cap:
                        if self._compact_locked(session_id, blk, events):
                            info["kind"] = "compact"
                            info["chain_len"] = 0
                        else:
                            info["compact_deferred"] = True
            if info is not None:
                blk = self._table[session_id]
                info["session_pages"] = len(blk.delta_pages()) + (
                    len(blk.base.pages) if blk.base.refs == 1 else 0
                )
                info["base_pages"] = len(blk.base.pages)
                info["base_refs"] = blk.base.refs
        self._flush(events)
        return info

    def read_block(self, session_id: str) -> Optional[np.ndarray]:
        """HOST copy of one session's [n_tokens, L, d] columns — the
        tests' parity window and the cold-path fallback, NOT the warm
        dispatch path (which takes pages in-graph)."""
        got = self.lookup(session_id)
        if got is None:
            return None
        pages, n_tokens = got
        key = len(pages)
        if key not in self._gather_fns:
            import jax

            pt = self.page_tokens
            L, d = self.cfg.levels, self.cfg.dim

            def fn(pool, idx):
                return pool[idx].reshape(key * pt, L, d)

            self._gather_fns[key] = jax.jit(fn)
        import jax.numpy as jnp

        # The gather runs OUTSIDE the lock but under a read pin: without
        # it an aliased write-back could donate (kill) the snapshot
        # mid-gather.
        buf = self.acquire_read()
        try:
            flat = self._gather_fns[key](
                buf, jnp.asarray(np.asarray(pages, np.int32))
            )
            return np.asarray(flat)[:n_tokens]
        finally:
            self.release_read()

    def defrag(self) -> int:
        """Compact allocated, UNPINNED pages toward low indices (one
        device gather/scatter from the pre-move buffer, so overlapping
        src/dst ranges read original values). Returns pages moved;
        stamps page_defrag. Allocation never NEEDS this (the take is
        index-addressed) — it is a locality/accounting pass for
        long-lived pools."""
        import jax.numpy as jnp

        if self.delta:
            # Delta blocks interleave shared bases and chain pages; the
            # take is index-addressed, so locality compaction buys
            # nothing a chain compaction doesn't — skip rather than move
            # pages a sibling session aliases.
            return 0
        with self._lock:
            blocks = sorted(
                (
                    (sid, blk)
                    for sid, blk in self._table.items()
                    if blk.pins == 0
                ),
                key=lambda kv: min(kv[1].pages),
            )
            pinned_pages = {
                p
                for blk in self._table.values()
                if blk.pins > 0
                for p in blk.pages
            }
            # Targets: lowest indices not owned by pinned blocks.
            targets = iter(
                i for i in range(self.n_pages) if i not in pinned_pages
            )
            src: List[int] = []
            dst: List[int] = []
            for sid, blk in blocks:
                new_pages = []
                for p in blk.pages:
                    t = next(targets)
                    new_pages.append(t)
                    if t != p:
                        src.append(p)
                        dst.append(t)
                blk.pages = new_pages
            if not src:
                return 0
            used_pages = {
                p for blk in self._table.values() for p in blk.pages
            }
            self._free = sorted(
                (i for i in range(self.n_pages) if i not in used_pages),
                reverse=True,
            )
            self._buffer = self._buffer.at[
                jnp.asarray(np.asarray(dst, np.int32))
            ].set(self._buffer[jnp.asarray(np.asarray(src, np.int32))])
            self.n_defrag_moves += len(src)
            ev = {
                "event": "page_defrag",
                "n_moved": len(src),
                "pages_used": self.n_pages - len(self._free),
                "pages_total": self.n_pages,
            }
        self._flush([ev])
        return len(src)

    def release(self) -> None:
        """A drained engine's device release (serve/elastic.py): free
        every block (one stamped page_free totals event), then drop the
        HBM buffer reference itself — the bytes a scaled-in replica was
        holding. The pool stays a valid accounting husk (record() keeps
        working) but any further write/read fails loudly on the None
        buffer — a dispatch against a released pool is a
        fleet-bookkeeping bug, not a degraded mode."""
        self.free_all(reason="drain-release")
        with self._lock:
            self._buffer = None

    # -- observability -----------------------------------------------------

    def _flush(self, events) -> None:
        from glom_tpu.serve.events import emit_serve

        for rec in events:
            if rec:
                emit_serve(self.writer, dict(rec, engine=self.name))

    def record(self) -> dict:
        """The pool rollup the batcher nests under its summary: capacity
        and churn in the live-bytes form (pages x page_state_bytes), the
        conservation pair the churn test reads (pages_used + pages_free
        == pages_total always)."""
        with self._lock:
            used = self.n_pages - len(self._free)
            rec = {
                "page_tokens": self.page_tokens,
                "page_bytes": self.page_bytes,
                "pages_total": self.n_pages,
                "pages_used": used,
                "pages_free": len(self._free),
                "pages_peak": self._pages_peak,
                "pool_bytes": self.pool_bytes,
                "bytes_in_use": used * self.page_bytes,
                "n_sessions": len(self._table),
                "n_allocs": self.n_allocs,
                "n_frees": self.n_frees,
                "n_alloc_fails": self.n_alloc_fails,
                "n_writebacks": self.n_writebacks,
                "n_defrag_moves": self.n_defrag_moves,
                # CoW traffic priced analytically (whole pool per CoW
                # write) — the aliasing A/B's baseline side, present
                # with aliasing off so the comparison has both arms.
                "cow_bytes_moved": self.cow_bytes_moved,
            }
            if self.aliasing:
                writes = self.n_alias_writes + self.n_alias_fallbacks
                rec["alias"] = {
                    "epoch": self._epoch,
                    "n_alias_writes": self.n_alias_writes,
                    "n_alias_fallbacks": self.n_alias_fallbacks,
                    "alias_bytes_moved": self.alias_bytes_moved,
                    "alias_rate": (
                        round(self.n_alias_writes / writes, 4)
                        if writes else None
                    ),
                }
            if self.delta:
                # The delta rollup the acceptance reads: bytes_per_stream
                # is ACTUAL pool pages over live sessions (shared bases
                # and sparse chains both shrink it — the several-fold
                # drop the delta cache exists for), chain stats price the
                # reconstruction depth, and the atol is the explicit
                # tolerance stamp the compare gate reads (0.0 = bitwise).
                chains = [
                    len(b.deltas)
                    for b in self._table.values()
                    if isinstance(b, _DeltaBlock)
                ]
                rec["delta"] = {
                    "delta_page_atol": self.delta_page_atol,
                    "delta_chain_cap": self.delta_chain_cap,
                    "bytes_per_stream": (
                        round(used * self.page_bytes / len(self._table), 1)
                        if self._table
                        else None
                    ),
                    "delta_chain_len_mean": (
                        round(sum(chains) / len(chains), 3) if chains else 0.0
                    ),
                    "delta_chain_len_max": max(chains) if chains else 0,
                    "n_delta_writes": self.n_delta_writes,
                    "n_delta_pages": self.n_delta_pages,
                    "n_delta_empty": self.n_delta_empty,
                    "n_compactions": self.n_compactions,
                    "n_compact_deferred": self.n_compact_deferred,
                    "n_base_shares": self.n_base_shares,
                    "n_superseded": self.n_superseded,
                }
            return rec


def resolve_page_pool(
    cfg, scfg, *, writer=None, name: str = "engine0", pool_sharding=None
) -> Optional[PagedColumnPool]:
    """The one config -> pool resolution: `page_pool_pages > 0` builds
    the device pool, 0 keeps the PR 8 host-array column cache."""
    if getattr(scfg, "page_pool_pages", 0) <= 0:
        return None
    return PagedColumnPool(
        cfg, scfg, writer=writer, name=name, pool_sharding=pool_sharding
    )
