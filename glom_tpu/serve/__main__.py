"""`python -m glom_tpu.serve ...` — the serving micro-server entry point
(serve/cli.py; `-m glom_tpu.serve.cli` works too but trips runpy's
already-imported warning, same as the telemetry CLI)."""

import sys

if __name__ == "__main__":
    from glom_tpu.serve.cli import main

    sys.exit(main(sys.argv[1:]))
