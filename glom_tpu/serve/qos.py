"""Multi-tenant QoS: named SLO classes + the weighted-fair admission lane.

One fleet serves mixed tenants honestly (docs/SERVING.md "SLO classes"):
a request carries an SLO CLASS ("premium" | "standard" | "batch" by
convention, any names work) declared in `ServeConfig.slo_classes`, and
the class survives every hop — admission, degradation, shedding,
autoscaling evidence, and audit. Three pieces live here:

  * `SLOClass` / `QosSpec` — the parsed, validated class table: per-class
    weight, optional p99/shed-rate targets, per-class queue depth, the
    shed order, and the batch starvation floor. `resolve_slo_classes`
    builds the spec from a ServeConfig (ServeConfig.__post_init__ calls
    it too, so a typo'd class table fails at construction, not
    mid-traffic).
  * `ClassQueues` — the deficit-weighted-fair admission scheduler: a
    drop-in for the batcher's shared `queue.Queue` (get / get_nowait /
    put_nowait / qsize / empty / maxsize) backed by PER-CLASS BOUNDED
    lanes, so batch backpressure can never fill premium's lane. Picks
    are strict-priority (highest weight first) EXCEPT that every lower
    class banks `starvation_floor` credit per pick and preempts the
    moment it is owed a whole pick — under sustained overload every
    backlogged class's served share is bounded below by the floor, and
    premium takes everything else.
  * per-class LADDER GATES — which degradation rung starts capping /
    shedding each class (resilience/ladder.class_rungs): the first class
    in the shed order degrades and sheds a rung early, the last (the
    premium end) holds its full route until the ladder's own high-water
    rungs.

Everything here is pure stdlib — importable without jax, like the
ServeConfig it validates. A config WITHOUT `slo_classes` never touches
this module: the batcher keeps its plain shared `queue.Queue` and the
PR 18 scheduling byte-for-byte (the classless bit-parity pin,
tests/test_qos.py).
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "SLOClass",
    "QosSpec",
    "ClassQueues",
    "parse_slo_class",
    "resolve_slo_classes",
    "class_slo_rules",
]

# Spec keys a class declaration may carry ("name:key=value,key=value").
_CLASS_KEYS = ("weight", "p99_ms", "shed_rate", "queue_depth")

# Credit never banks more than this many whole picks: a class that idled
# for an hour must not monopolize the lane when its backlog returns —
# the floor bounds the RATE, not an unbounded debt.
_CREDIT_CAP = 2.0


@dataclass(frozen=True)
class SLOClass:
    """One named SLO class: scheduling weight + its own targets."""

    name: str
    weight: float = 1.0
    # Per-class SLO targets: armed as class-scoped monitor rules
    # ("p99_ms[premium]=X" — telemetry/aggregate.parse_slo) when set.
    p99_ms: Optional[float] = None
    shed_rate: Optional[float] = None
    # Per-class admission lane depth; None = the shared queue_depth.
    queue_depth: Optional[int] = None


def parse_slo_class(spec: str) -> SLOClass:
    """'premium:weight=8,p99_ms=150' -> SLOClass. Loud on malformed
    specs (a typo'd class table that silently serves FIFO is worse than
    none)."""
    name, sep, rest = spec.partition(":")
    name = name.strip()
    if not name:
        raise ValueError(f"slo_classes entry {spec!r}: empty class name")
    kw: Dict[str, float] = {}
    if sep and rest.strip():
        for part in rest.split(","):
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq or key not in _CLASS_KEYS:
                raise ValueError(
                    f"slo_classes entry {spec!r}: expected KEY=VALUE with "
                    f"KEY one of {_CLASS_KEYS}, got {part!r}"
                )
            try:
                kw[key] = float(val)
            except ValueError:
                raise ValueError(
                    f"slo_classes entry {spec!r}: {key} value {val!r} is "
                    "not a number"
                ) from None
    weight = kw.pop("weight", 1.0)
    if weight <= 0:
        raise ValueError(f"slo_classes entry {spec!r}: weight must be > 0")
    depth = kw.pop("queue_depth", None)
    if depth is not None:
        if depth != int(depth) or depth < 1:
            raise ValueError(
                f"slo_classes entry {spec!r}: queue_depth must be an "
                "int >= 1"
            )
        depth = int(depth)
    p99 = kw.pop("p99_ms", None)
    if p99 is not None and p99 <= 0:
        raise ValueError(f"slo_classes entry {spec!r}: p99_ms must be > 0")
    shed = kw.pop("shed_rate", None)
    if shed is not None and not 0.0 <= shed <= 1.0:
        raise ValueError(
            f"slo_classes entry {spec!r}: shed_rate must be in [0, 1]"
        )
    return SLOClass(
        name=name, weight=weight, p99_ms=p99, shed_rate=shed,
        queue_depth=depth,
    )


@dataclass(frozen=True)
class QosSpec:
    """The validated class table. `classes` is PRIORITY order (highest
    weight first — the strict-preference order); `shed_order` is the
    reverse story: its FIRST entry degrades and sheds first, its LAST
    holds out longest."""

    classes: Tuple[SLOClass, ...]
    shed_order: Tuple[str, ...]
    default_class: str
    starvation_floor: float

    def __post_init__(self):
        object.__setattr__(
            self, "_by_name", {c.name: c for c in self.classes}
        )

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(c.name for c in self.classes)

    def class_of(self, name: str) -> SLOClass:
        return self._by_name[name]

    def weights(self) -> Dict[str, float]:
        return {c.name: c.weight for c in self.classes}

    def resolve(self, slo_class: Optional[str]) -> str:
        """Admission-time class resolution: None takes the default; an
        UNDECLARED name is a caller bug, rejected loudly before any
        counter moves."""
        if slo_class is None:
            return self.default_class
        if slo_class not in self._by_name:
            raise ValueError(
                f"slo_class {slo_class!r} is not declared; "
                f"slo_classes = {list(self.names)}"
            )
        return slo_class

    def shed_position(self, name: str) -> int:
        """0 = first to shed/degrade; len-1 = the premium end."""
        return self.shed_order.index(name)

    def _gates(self, name: str) -> Tuple[int, int]:
        from glom_tpu.resilience.ladder import class_rungs

        return class_rungs(self.shed_position(name), len(self.classes))

    def degrade_rung(self, name: str) -> int:
        """The ladder rung at which this class's dispatches take the
        capped-iters route (the premium end holds its full route one
        rung longer — resilience/ladder.class_rungs)."""
        return self._gates(name)[0]

    def shed_rung(self, name: str) -> int:
        """The ladder rung at which admission sheds this class (the
        first class in the shed order sheds a rung EARLY — load drops
        tenant-by-tenant, batch first)."""
        return self._gates(name)[1]

    def low_classes(self) -> frozenset:
        """Classes whose SLO breaches are NON-BINDING for the elastic
        policy (the first entry of the shed order): batch-only pressure
        must not force a scale-out nor veto an earned scale-in — those
        calls belong to the classes the fleet actually protects."""
        if len(self.shed_order) < 2:
            return frozenset()
        return frozenset({self.shed_order[0]})


def resolve_slo_classes(scfg) -> Optional[QosSpec]:
    """The ONE ServeConfig -> QosSpec resolution (None when the config
    declares no classes — the classless bit-parity path). Loud on every
    inconsistency: duplicate names, an unknown default or shed-order
    name, a floor the class count cannot satisfy."""
    specs = getattr(scfg, "slo_classes", None)
    if not specs:
        return None
    parsed = [parse_slo_class(s) for s in specs]
    names = [c.name for c in parsed]
    if len(set(names)) != len(names):
        raise ValueError(f"slo_classes {names}: duplicate class names")
    # Priority = descending weight; declaration order breaks ties (so
    # ("premium:weight=4", "standard", "batch") reads top-down).
    order = sorted(
        range(len(parsed)), key=lambda i: (-parsed[i].weight, i)
    )
    classes = tuple(parsed[i] for i in order)
    shed_order = getattr(scfg, "slo_shed_order", None)
    if shed_order:
        if sorted(shed_order) != sorted(names):
            raise ValueError(
                f"slo_shed_order {list(shed_order)} must be a permutation "
                f"of the declared classes {sorted(names)}"
            )
        shed_order = tuple(shed_order)
    else:
        # Default shed order: ascending priority — the lightest-weight
        # class sheds first, the heaviest holds out longest.
        shed_order = tuple(c.name for c in reversed(classes))
    default = getattr(scfg, "slo_default_class", None)
    if default is None:
        default = "standard" if "standard" in names else classes[0].name
    elif default not in names:
        raise ValueError(
            f"slo_default_class {default!r} is not a declared class "
            f"{sorted(names)}"
        )
    floor = float(getattr(scfg, "slo_starvation_floor", 0.05))
    if not 0.0 <= floor < 1.0:
        raise ValueError(
            f"slo_starvation_floor {floor} must be in [0, 1)"
        )
    if len(classes) > 1 and (len(classes) - 1) * floor >= 1.0:
        raise ValueError(
            f"slo_starvation_floor {floor} x {len(classes) - 1} lower "
            "classes leaves the top class no capacity — the floor must "
            "satisfy (n_classes - 1) * floor < 1"
        )
    return QosSpec(
        classes=classes, shed_order=shed_order, default_class=default,
        starvation_floor=floor,
    )


def class_slo_rules(spec: QosSpec) -> Dict[str, float]:
    """Class-scoped monitor rules from the per-class targets:
    {"p99_ms[premium]": 150.0, "shed_rate[batch]": 0.2, ...} — the
    vocabulary telemetry/aggregate.parse_slo speaks and the elastic
    loop arms (docs/OBSERVABILITY.md)."""
    rules: Dict[str, float] = {}
    for c in spec.classes:
        if c.p99_ms is not None:
            rules[f"p99_ms[{c.name}]"] = c.p99_ms
        if c.shed_rate is not None:
            rules[f"shed_rate[{c.name}]"] = c.shed_rate
    return rules


class ClassQueues:
    """The deficit-weighted-fair admission lane: a drop-in for the
    batcher's shared `queue.Queue` backed by one BOUNDED deque per
    class.

    Scheduling contract (docs/SERVING.md "SLO classes"):

      * put_nowait(item) routes by `item.slo_class` into that class's
        lane and raises `queue.Full` when THAT lane is at capacity —
        per-class backpressure, so a batch flood can never occupy
        premium's admission slots;
      * get()/get_nowait() pick STRICT-PRIORITY (highest weight first)
        — except the starvation floor: every non-top backlogged class
        banks `starvation_floor` credit per pick and preempts the
        moment it is owed a whole pick (lowest class checked first).
        Under sustained all-class overload every class's pick share is
        therefore >= the floor, premium takes the remainder — the
        bound tests/test_qos.py pins;
      * qsize()/empty()/maxsize read the TOTAL across lanes (the shape
        the ladder's queue-fill signal and the capacity records expect).

    Thread-safe under one condition variable; `record()` exposes the
    per-class pick/occupancy evidence the summary nests."""

    def __init__(self, spec: QosSpec, *, default_depth: int):
        if default_depth < 1:
            raise ValueError(f"default_depth {default_depth} must be >= 1")
        self.spec = spec
        self._order: List[str] = list(spec.names)  # priority, highest 1st
        self._lanes: Dict[str, deque] = {n: deque() for n in self._order}
        self._depth: Dict[str, int] = {
            c.name: (
                c.queue_depth if c.queue_depth is not None else default_depth
            )
            for c in spec.classes
        }
        self.maxsize = sum(self._depth.values())
        self._cv = threading.Condition()
        self._size = 0
        self._n_picks = 0
        self._picks: Dict[str, int] = {n: 0 for n in self._order}
        self._credit: Dict[str, float] = {n: 0.0 for n in self._order}
        self.n_floor_picks = 0
        self.n_full: Dict[str, int] = {n: 0 for n in self._order}

    # -- queue.Queue facade -------------------------------------------------

    def qsize(self) -> int:
        with self._cv:
            return self._size

    def empty(self) -> bool:
        return self.qsize() == 0

    def put_nowait(self, item) -> None:
        cls = getattr(item, "slo_class", None) or self.spec.default_class
        lane = self._lanes.get(cls)
        if lane is None:
            # submit() resolves classes before enqueue; an unknown class
            # here is a requeue of a pre-reconfiguration item — route it
            # to the default lane rather than strand the ticket.
            cls = self.spec.default_class
            lane = self._lanes[cls]
        with self._cv:
            if len(lane) >= self._depth[cls]:
                self.n_full[cls] += 1
                raise queue.Full
            lane.append(item)
            self._size += 1
            self._cv.notify()

    def get_nowait(self):
        return self.get(timeout=0.0)

    def get(self, timeout: Optional[float] = None):
        with self._cv:
            if timeout is None:
                while self._size == 0:
                    self._cv.wait()
            elif self._size == 0 and timeout > 0:
                self._cv.wait_for(lambda: self._size > 0, timeout)
            if self._size == 0:
                raise queue.Empty
            cls = self._pick_locked()
            item = self._lanes[cls].popleft()
            self._size -= 1
            return item

    # -- the deficit-weighted-fair pick -------------------------------------

    def _pick_locked(self) -> str:
        backlogged = [c for c in self._order if self._lanes[c]]
        top = self._order[0]
        chosen = None
        floor_pick = False
        # The starvation floor first, LOWEST priority first: a class
        # that has banked a whole owed pick takes this slot regardless
        # of what premium has queued.
        for c in reversed(self._order):
            if c != top and self._lanes[c] and self._credit[c] >= 1.0:
                chosen, floor_pick = c, True
                break
        if chosen is None:
            chosen = backlogged[0]  # strict preference
        # Every OTHER backlogged non-top class banks its floor credit
        # for this pick; the chosen class pays a whole pick down.
        floor = self.spec.starvation_floor
        for c in backlogged:
            if c != top and c != chosen:
                self._credit[c] = min(
                    _CREDIT_CAP, self._credit[c] + floor
                )
        if chosen != top:
            self._credit[chosen] = max(
                0.0, self._credit[chosen] + floor - 1.0
            )
        self._n_picks += 1
        self._picks[chosen] += 1
        if floor_pick:
            self.n_floor_picks += 1
        return chosen

    # -- evidence -----------------------------------------------------------

    def class_fill(self) -> Dict[str, Dict[str, int]]:
        """{class: {"depth": queued, "capacity": lane bound}} — the
        per-class pressure the shed details and capacity records carry."""
        with self._cv:
            return {
                n: {"depth": len(self._lanes[n]), "capacity": self._depth[n]}
                for n in self._order
            }

    def record(self) -> dict:
        """The scheduler rollup the batcher summary nests: per-class
        picks, the floor-preemption count, and rejected-at-lane-full
        counts (conservation: picks sum to every get() that returned)."""
        with self._cv:
            return {
                "starvation_floor": self.spec.starvation_floor,
                "n_picks": self._n_picks,
                "n_floor_picks": self.n_floor_picks,
                "picks": dict(self._picks),
                "lane_full": {
                    n: v for n, v in self.n_full.items() if v
                },
            }
