"""Session-keyed warm-start column cache: carry converged columns across
temporal frames.

GLOM's "islands of agreement" persist across the frames of a stream — a
request that starts from the PREVIOUS frame's converged column state is
already sitting near the consensus attractor, so the `iters="auto"` exit
fires in a fraction of the cold-start budget. This module is the O(1)
state-reuse pattern from the compiler-first autoregressive-caching
literature (PAPERS.md) applied to consensus state: the cached unit is one
session's `[n, L, d]` column tensor, written back after every resolved
request that carries a `session_id` and read at the NEXT dispatch as the
warm `levels0` init (the engine's existing warm-signature machinery — no
new compiled programs).

Residency discipline:

  * PRICED — every entry costs `column_state_bytes(cfg, scfg)` of the
    serving replica's HBM while a warm dispatch stages it (the same
    analytic live-bytes accounting utils/metrics.py prices train state
    with); the cache holds the HOST copy (device buffers are donated per
    dispatch and cannot be retained), but the budget is an HBM budget:
    entries beyond `ServeConfig.column_cache_bytes` evict LRU-first, and
    total resident bytes NEVER exceed the budget — an entry larger than
    the whole budget is rejected outright, not "temporarily" overcommitted;
  * TTL — a stream that went quiet is stale state, not warmth:
    `column_cache_ttl_s` expires an entry at lookup time (a hit on an
    expired entry is a MISS plus an eviction, stamped as such);
  * INVALIDATED on engine death/failover — entries are tagged with the
    engine that produced them, and the batcher drops an engine's entries
    the moment a dispatch on it fails (`invalidate_engine`), so a stale
    or dead-engine entry can never warm-start a request;
  * OBSERVED — hits/misses/evictions/expirations/invalidations and the
    live byte count are counters on `record()` (rolled into the batcher's
    summary), and every eviction/expiry/invalidation is a stamped "serve"
    event through the usual writer-else-flight delivery.

Thread-safe: lookups run on the batcher's per-engine worker threads while
stores/invalidations run on workers and the caller; one lock guards the
LRU map and every counter (events are emitted OUTSIDE the lock — the
writer may block on IO).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Dict, List, NamedTuple, Optional

if TYPE_CHECKING:  # import cycle: paged_columns never imports back
    from glom_tpu.serve.paged_columns import PagedColumnPool

import numpy as np


class PageHit(NamedTuple):
    """A PAGES-mode cache hit (serve/paged_columns.py): the warm state is
    device-resident — the dispatch carries these page indices into the
    engine's paged signature instead of a host array. `engine` names the
    pool (and the session-affinity routing target); the hit arrives
    PINNED when looked up with pin=True — the caller unpins after the
    dispatch snapshot (ColumnCache.unpin)."""

    engine: str
    pages: List[int]
    n_tokens: int


def column_state_bytes(cfg, scfg) -> int:
    """The live-bytes price of ONE session's cached column state: the
    `[num_patches, levels, dim]` tensor in the serving compute dtype —
    the same analytic form the HBM accounting prices the warm `levels0`
    staging buffer with. This is what `ServeConfig.column_cache_bytes`
    is divided by when sizing a deployment (docs/SERVING.md,
    "Streaming")."""
    itemsize = 2 if scfg.compute_dtype == "bfloat16" else 4
    return cfg.num_patches * cfg.levels * cfg.dim * itemsize


class _Entry:
    __slots__ = (
        "levels", "nbytes", "engine", "t_write", "n_tokens", "prev_input",
    )

    def __init__(
        self,
        levels: Optional[np.ndarray],
        engine: str,
        t_write: float,
        *,
        nbytes: Optional[int] = None,
        n_tokens: int = 0,
    ):
        self.levels = levels  # host array, or None in PAGES mode
        self.nbytes = int(
            nbytes if nbytes is not None else levels.nbytes
        )
        self.engine = engine
        self.t_write = t_write
        self.n_tokens = n_tokens
        # DELTA mode: the previous frame's host-patchified input
        # [n, patch_dim] — the reference the next frame's INPUT delta
        # support is computed against (input_support; host RAM, never
        # HBM, and only retained when delta streaming is on).
        self.prev_input: Optional[np.ndarray] = None


class ColumnCache:
    """LRU column-state cache keyed by session id, bounded in bytes.

    `budget_bytes` is the hard residency ceiling (HBM-priced via
    column_state_bytes); `ttl_s=None` disables expiry. The clock is
    injectable so TTL tests never sleep.

    PAGES MODE (`pools={engine_name: PagedColumnPool}`): entries become
    PAGE-TABLE REFERENCES — store() writes the converged columns
    device-to-device into the named engine's pool and lookup() returns a
    `PageHit` (engine + page indices) instead of a host array; eviction,
    TTL expiry, and invalidation FREE PAGES instead of dropping host
    arrays. The residency policy (LRU under the byte budget, TTL, engine
    invalidation) is unchanged — each entry is priced at its allocated
    pages x page_state_bytes, and pool exhaustion reads as eviction
    pressure exactly like the byte budget does. LOCK ORDER: the cache
    lock is taken BEFORE any pool lock, never the reverse (pools never
    call back into the cache)."""

    def __init__(
        self,
        budget_bytes: int,
        *,
        ttl_s: Optional[float] = None,
        writer=None,
        clock=time.monotonic,
        pools: Optional[Dict[str, "PagedColumnPool"]] = None,
    ):
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes {budget_bytes} must be >= 1")
        if ttl_s is not None and ttl_s <= 0:
            raise ValueError(f"ttl_s {ttl_s} must be > 0 or None")
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = ttl_s
        self.writer = writer
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.pools = dict(pools) if pools else None
        # DELTA mode (docs/SERVING.md, "Delta streaming"): pools built
        # from a delta_streaming config store base+delta chains instead
        # of whole-row blocks; the cache's byte accounting then prices
        # ACTUAL pool pages (shared bases counted once, chains at their
        # real sparse size) — the "several-fold more live streams in the
        # same budget" claim is this recount, not an estimate.
        self.delta = bool(self.pools) and any(
            getattr(p, "delta", False) for p in self.pools.values()
        )
        self._bytes = 0
        self._peak_bytes = 0
        self.n_hits = 0
        self.n_misses = 0
        self.n_writes = 0
        self.n_evictions = 0
        self.n_expirations = 0
        self.n_invalidations = 0
        self.n_rejects = 0

    # -- the request path --------------------------------------------------

    def engine_of(self, session_id: str) -> Optional[str]:
        """Which engine's pool holds the session's pages (None when
        absent or the cache is in host mode) — the SESSION-AFFINITY
        routing read (serve/batcher.py routes a stream to the engine
        holding its pages). A peek: no LRU touch, no counters."""
        with self._lock:
            if self.pools is None:
                return None
            entry = self._entries.get(session_id)
            return entry.engine if entry is not None else None

    def lookup(self, session_id: str, *, pin: bool = False):
        """The session's cached column state (freshest-first LRU touch),
        or None on miss: the host [n, L, d] array, or a `PageHit` in
        pages mode. An entry past its TTL is dropped HERE — an expired
        stream must never warm-start a request — and counts as one
        expiration plus the miss. pin=True (pages mode) read-pins the
        block so eviction cannot re-issue its pages while the dispatch
        reads them — callers unpin() after the dispatch."""
        events: List[dict] = []
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                self.n_misses += 1
                return None
            if (
                self.ttl_s is not None
                and self._clock() - entry.t_write > self.ttl_s
            ):
                self._drop(session_id, entry)
                self.n_expirations += 1
                self.n_misses += 1
                events.append(
                    {
                        "event": "cache_expire",
                        "session": session_id,
                        "bytes": entry.nbytes,
                        "age_s": round(self._clock() - entry.t_write, 3),
                    }
                )
                out = None
            else:
                self._entries.move_to_end(session_id)
                self.n_hits += 1
                if self.pools is not None:
                    got = self.pools[entry.engine].lookup(
                        session_id, pin=pin
                    )
                    if got is None:  # pool lost the block (force-free)
                        self._drop(session_id, entry)
                        self.n_hits -= 1
                        self.n_misses += 1
                        out = None
                    else:
                        out = PageHit(entry.engine, got[0], got[1])
                else:
                    out = entry.levels
        self._flush(events)
        return out

    def unpin(self, session_id: str) -> None:
        """Release a pin taken by lookup(pin=True) (pages mode no-op
        otherwise)."""
        with self._lock:
            if self.pools is None:
                return
            entry = self._entries.get(session_id)
            pool = (
                self.pools.get(entry.engine) if entry is not None else None
            )
        if pool is not None:
            pool.unpin(session_id)

    def _sweep_expired_locked(self, events: List[dict]) -> int:
        """Drop EVERY expired entry (caller holds the lock) — the
        eviction-pressure sweep: TTL otherwise fires only at lookup, so
        a dead session's bytes (pages) stay pinned until someone touches
        the key. Under pressure the sweep reclaims them FIRST, before
        any live LRU victim pays (stamped cache_expire like the lookup
        path — one leak, one event vocabulary)."""
        if self.ttl_s is None:
            return 0
        now = self._clock()
        expired = [
            (sid, e)
            for sid, e in self._entries.items()
            if now - e.t_write > self.ttl_s
        ]
        for sid, entry in expired:
            self._drop(sid, entry)
            self.n_expirations += 1
            events.append(
                {
                    "event": "cache_expire",
                    "session": sid,
                    "bytes": entry.nbytes,
                    "age_s": round(now - entry.t_write, 3),
                    "swept": True,
                }
            )
        return len(expired)

    def _evict_lru_locked(self, events: List[dict], *, skip=()) -> bool:
        """Evict the least-recently-used UNPINNED entry (caller holds
        the lock). False when nothing evictable remains."""
        for victim_id, victim in self._entries.items():
            if victim_id in skip:
                continue
            if (
                self.pools is not None
                and self.pools[victim.engine].is_pinned(victim_id)
            ):
                continue  # an in-flight dispatch is reading these pages
            self._drop(victim_id, victim)
            self.n_evictions += 1
            events.append(
                {
                    "event": "cache_evict",
                    "session": victim_id,
                    "bytes": victim.nbytes,
                    "bytes_in_use": self._bytes,
                    "budget_bytes": self.budget_bytes,
                }
            )
            return True
        return False

    def store(
        self,
        session_id: str,
        levels,
        *,
        engine: str,
        n_tokens: Optional[int] = None,
        patches: Optional[np.ndarray] = None,
        content_hash: Optional[str] = None,
    ) -> bool:
        """Write one resolved request's converged columns back under its
        session key (the warm init for the stream's NEXT frame), evicting
        LRU entries until the byte budget holds. Returns False when the
        entry alone exceeds the whole budget (rejected, stamped — the
        budget is a ceiling, never overcommitted).

        PAGES mode: `levels` is the DEVICE row slice and `n_tokens` its
        patch count — the columns go device-to-device into the engine's
        pool (never the host). Eviction pressure (byte budget OR pool
        exhaustion) first SWEEPS expired entries, then evicts live LRU
        victims; pinned blocks (in-flight readers) are skipped."""
        now = self._clock()
        events: List[dict] = []
        with self._lock:
            pages_mode = self.pools is not None
            pool = self.pools[engine] if pages_mode else None
        if pages_mode:
            if n_tokens is None:
                raise ValueError("pages mode store() needs n_tokens")
            if self.delta and getattr(pool, "delta", False):
                return self._store_delta(
                    session_id, levels, engine, n_tokens, pool, now,
                    patches=patches, content_hash=content_hash,
                )
            from glom_tpu.serve.paged_columns import pages_for_tokens

            need_pages = pages_for_tokens(n_tokens, pool.page_tokens)
            nbytes = need_pages * pool.page_bytes
            with self._lock:
                if (
                    nbytes > self.budget_bytes
                    or need_pages > pool.n_pages
                ):
                    self.n_rejects += 1
                    events.append(
                        {
                            "event": "cache_reject",
                            "session": session_id,
                            "bytes": nbytes,
                            "budget_bytes": min(
                                self.budget_bytes,
                                pool.n_pages * pool.page_bytes,
                            ),
                        }
                    )
                    self._flush(events)
                    return False
                old = self._entries.pop(session_id, None)
                if old is not None:
                    self._bytes -= old.nbytes
                    if old.engine != engine:
                        # The stream moved engines (failover): its old
                        # pages live in the OLD pool — free them there.
                        self.pools[old.engine].free(
                            session_id, reason="moved"
                        )
                # Byte-budget pressure: sweep expired first, then LRU.
                swept = False
                while self._bytes + nbytes > self.budget_bytes:
                    if not swept:
                        swept = True
                        if self._sweep_expired_locked(events):
                            continue
                    if not self._evict_lru_locked(
                        events, skip=(session_id,)
                    ):
                        break
                # Pool pressure: the write-back allocates; exhaustion is
                # eviction pressure too (same sweep-then-LRU order; only
                # victims in THIS pool free the pages we need).
                stored = pool.write_back(session_id, levels, n_tokens)
                while not stored:
                    if not swept:
                        swept = True
                        if self._sweep_expired_locked(events):
                            stored = pool.write_back(
                                session_id, levels, n_tokens
                            )
                            continue
                    evicted = False
                    for vid, victim in list(self._entries.items()):
                        if vid == session_id or victim.engine != engine:
                            continue
                        if pool.is_pinned(vid):
                            continue
                        self._drop(vid, victim)
                        self.n_evictions += 1
                        events.append(
                            {
                                "event": "cache_evict",
                                "session": vid,
                                "bytes": victim.nbytes,
                                "bytes_in_use": self._bytes,
                                "budget_bytes": self.budget_bytes,
                            }
                        )
                        evicted = True
                        break
                    if not evicted:
                        break
                    stored = pool.write_back(session_id, levels, n_tokens)
                if not stored:
                    self.n_rejects += 1
                    events.append(
                        {
                            "event": "cache_reject",
                            "session": session_id,
                            "bytes": nbytes,
                            "budget_bytes": self.budget_bytes,
                            "reason": "pool-exhausted",
                        }
                    )
                else:
                    entry = _Entry(
                        None, engine, now, nbytes=nbytes, n_tokens=n_tokens
                    )
                    self._entries[session_id] = entry
                    self._bytes += entry.nbytes
                    self.n_writes += 1
                    self._peak_bytes = max(self._peak_bytes, self._bytes)
            self._flush(events)
            return stored
        levels = np.asarray(levels)
        with self._lock:
            if int(levels.nbytes) > self.budget_bytes:
                self.n_rejects += 1
                events.append(
                    {
                        "event": "cache_reject",
                        "session": session_id,
                        "bytes": int(levels.nbytes),
                        "budget_bytes": self.budget_bytes,
                    }
                )
                stored = False
            else:
                old = self._entries.pop(session_id, None)
                if old is not None:
                    self._bytes -= old.nbytes
                entry = _Entry(levels, engine, now)
                self._entries[session_id] = entry
                self._bytes += entry.nbytes
                self.n_writes += 1
                swept = False
                while self._bytes > self.budget_bytes:
                    # Eviction pressure: reclaim EXPIRED entries first
                    # (the TTL-at-lookup-only leak — a dead session's
                    # bytes stay pinned until someone touches the key),
                    # then live LRU victims.
                    if not swept:
                        swept = True
                        if self._sweep_expired_locked(events):
                            continue
                    if not self._evict_lru_locked(
                        events, skip=(session_id,)
                    ):
                        break
                self._peak_bytes = max(self._peak_bytes, self._bytes)
                stored = True
        self._flush(events)
        return stored

    def _store_delta(
        self,
        session_id: str,
        levels,
        engine: str,
        n_tokens: int,
        pool,
        now: float,
        *,
        patches: Optional[np.ndarray] = None,
        content_hash: Optional[str] = None,
    ) -> bool:
        """The DELTA-mode store: the pool lays down a base / appends a
        sparse delta / folds the chain (write_back_stream); the cache
        keeps residency policy — sweep-then-LRU under pool exhaustion AND
        under the byte budget, both priced on the pools' ACTUAL pages.
        Every outcome is a stamped event: cache_delta (base or sparse
        append, with the explicit atol the compare gate reads),
        cache_compact (chain folded), cache_share (base aliased)."""
        events: List[dict] = []
        with self._lock:
            old = self._entries.pop(session_id, None)
            if old is not None:
                self._bytes -= old.nbytes
                if old.engine != engine:
                    self.pools[old.engine].free(session_id, reason="moved")
            swept = False
            info = pool.write_back_stream(
                session_id, levels, n_tokens, content_hash=content_hash
            )
            while info is None:
                if not swept:
                    swept = True
                    if self._sweep_expired_locked(events):
                        info = pool.write_back_stream(
                            session_id, levels, n_tokens,
                            content_hash=content_hash,
                        )
                        continue
                evicted = False
                for vid, victim in list(self._entries.items()):
                    if vid == session_id or victim.engine != engine:
                        continue
                    if pool.is_pinned(vid):
                        continue
                    self._drop(vid, victim)
                    self.n_evictions += 1
                    events.append(
                        {
                            "event": "cache_evict",
                            "session": vid,
                            "bytes": victim.nbytes,
                            "bytes_in_use": self._bytes,
                            "budget_bytes": self.budget_bytes,
                        }
                    )
                    evicted = True
                    break
                if not evicted:
                    break
                info = pool.write_back_stream(
                    session_id, levels, n_tokens, content_hash=content_hash
                )
            if info is None:
                from glom_tpu.serve.paged_columns import pages_for_tokens

                self.n_rejects += 1
                events.append(
                    {
                        "event": "cache_reject",
                        "session": session_id,
                        "bytes": pages_for_tokens(n_tokens, pool.page_tokens)
                        * pool.page_bytes,
                        "budget_bytes": self.budget_bytes,
                        "reason": "pool-exhausted",
                    }
                )
                if old is not None and old.engine == engine:
                    # The failed append rolled nothing forward — the pool
                    # still holds the session's PREVIOUS state. Reinstate
                    # the entry so that block stays reachable (lookups
                    # serve the old frame's warmth) and EVICTABLE —
                    # popping it while the pool kept the pages would
                    # strand them outside every eviction walk.
                    self._entries[session_id] = old
                self._recount_locked()
                self._flush(events)
                return False
            nbytes = info["session_pages"] * pool.page_bytes
            entry = _Entry(
                None, engine, now, nbytes=nbytes, n_tokens=n_tokens
            )
            if patches is not None:
                entry.prev_input = np.ascontiguousarray(
                    np.asarray(patches, np.float32)
                )
            self._entries[session_id] = entry
            self.n_writes += 1
            self._recount_locked()
            # Budget pressure on ACTUAL bytes (shared bases counted once,
            # chains at their sparse size): sweep expired first, then LRU.
            while self._bytes > self.budget_bytes:
                if not swept:
                    swept = True
                    if self._sweep_expired_locked(events):
                        continue
                if not self._evict_lru_locked(events, skip=(session_id,)):
                    break
            event = {
                "base": "cache_delta",
                "delta": "cache_delta",
                "share": "cache_share",
                "compact": "cache_compact",
            }[info["kind"]]
            events.append(
                {
                    "event": event,
                    "session": session_id,
                    "kind": info["kind"],
                    "pages_written": info["pages_written"],
                    "chain_len": info["chain_len"],
                    "base_refs": info.get("base_refs"),
                    "bytes": nbytes,
                    "bytes_in_use": self._bytes,
                    "delta_page_atol": pool.delta_page_atol,
                    **(
                        {"empty": True} if info.get("empty") else {}
                    ),
                    **(
                        {"compact_deferred": True}
                        if info.get("compact_deferred")
                        else {}
                    ),
                }
            )
        self._flush(events)
        return True

    def input_support(
        self, session_id: str, patches: np.ndarray, page_tokens: int
    ) -> np.ndarray:
        """[n_pages] bool — which INPUT pages of this frame changed vs
        the session's previous frame (bitwise: a hold frame is empty
        support, a moving region is exactly its pages). No previous
        frame, or a resolution change, marks every page changed — the
        conservative seed (the row behaves like plain tiered exit). This
        is the support `glom_forward_incremental` seeds the witness
        from; pre-converged rows still pay the min_iters floor."""
        with self._lock:
            entry = self._entries.get(session_id)
            prev = entry.prev_input if entry is not None else None
        patches = np.asarray(patches, np.float32)
        n = patches.shape[0]
        n_pages = -(-n // page_tokens)
        if prev is None or prev.shape != patches.shape:
            return np.ones((n_pages,), bool)
        same = (
            patches.view(np.int32) == prev.view(np.int32)
        )  # bitcast compare: -0.0 vs 0.0 is a CHANGE
        out = np.zeros((n_pages,), bool)
        for k in range(n_pages):
            out[k] = not bool(
                same[k * page_tokens:(k + 1) * page_tokens].all()
            )
        return out

    # -- invalidation ------------------------------------------------------

    def invalidate(self, session_id: str, *, reason: str = "explicit") -> bool:
        """Drop one session's entry (stream ended, client reset)."""
        events: List[dict] = []
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                return False
            self._drop(session_id, entry)
            self.n_invalidations += 1
            events.append(
                {
                    "event": "cache_invalidate",
                    "session": session_id,
                    "reason": reason,
                    "bytes": entry.nbytes,
                }
            )
        self._flush(events)
        return True

    def invalidate_engine(self, engine: str, *, reason: str = "engine-failover") -> int:
        """Drop EVERY entry the named engine wrote — called by the
        batcher on a dispatch failure / engine death, so state produced
        near the failure can never warm-start a request. Returns how many
        entries were dropped."""
        events: List[dict] = []
        with self._lock:
            victims = [
                (sid, e) for sid, e in self._entries.items()
                if e.engine == engine
            ]
            for sid, entry in victims:
                self._drop(sid, entry)
                self.n_invalidations += 1
            if victims:
                events.append(
                    {
                        "event": "cache_invalidate",
                        "engine": engine,
                        "reason": reason,
                        "n_entries": len(victims),
                        "bytes": sum(e.nbytes for _, e in victims),
                    }
                )
        self._flush(events)
        return len(victims)

    # -- elastic drain (serve/elastic.py, docs/SERVING.md) -----------------

    def add_pool(self, engine: str, pool) -> None:
        """Register a runtime-added engine's pool (the batcher's
        add_engine calls this in pages mode)."""
        with self._lock:
            if self.pools is None:
                raise ValueError(
                    "add_pool on a host-mode cache (the fleet was built "
                    "without page pools)"
                )
            self.pools[engine] = pool

    def remove_pool(self, engine: str) -> None:
        """Unregister a drained engine's pool. Any entry still pointing
        at it (a migration raced a concurrent store) is invalidated
        first — an entry must never reference a pool the cache no
        longer knows."""
        events: List[dict] = []
        with self._lock:
            if self.pools is None or engine not in self.pools:
                return
            leftover = [
                (sid, e) for sid, e in self._entries.items()
                if e.engine == engine
            ]
            for sid, entry in leftover:
                self._drop(sid, entry)
                self.n_invalidations += 1
                events.append(
                    {
                        "event": "cache_invalidate",
                        "session": sid,
                        "engine": engine,
                        "reason": "drain",
                        "bytes": entry.nbytes,
                    }
                )
            self.pools.pop(engine, None)
        self._flush(events)

    def migrate_engine_sessions(
        self, src: str, dst: Optional[str], *, reason: str = "drain"
    ) -> dict:
        """Move every session whose state lives on `src` to `dst` — the
        drain state machine's migration step (docs/SERVING.md, "Elastic
        serving").

        HOST mode: the cached state is a host array ANY engine already
        warms from — the entry simply re-tags to `dst` (zero bytes
        moved). PAGES mode: each session's paged columns round-trip
        src-pool -> host -> dst-pool — a pure byte copy, so the sibling
        serves BITWISE the state the drained engine held (delta chains
        migrate as their resolved effective state and restart a fresh
        base on the destination). A session that cannot land — no
        destination, destination pool out of page budget, or pinned by
        an in-flight read — is INVALIDATED with the stamped `reason`:
        never silently dropped, never left pointing at a released pool.

        Returns {"n_migrated", "n_invalidated", "bytes_migrated"}."""
        out = {"n_migrated": 0, "n_invalidated": 0, "bytes_migrated": 0}
        with self._lock:
            sids = [
                sid for sid, e in self._entries.items() if e.engine == src
            ]
            host_mode = self.pools is None
            src_pool = None if host_mode else self.pools.get(src)
            dst_pool = (
                self.pools.get(dst)
                if not host_mode and dst is not None else None
            )
        events: List[dict] = []
        for sid in sids:
            if host_mode:
                if dst is None:
                    if self.invalidate(sid, reason=reason):
                        out["n_invalidated"] += 1
                    continue
                with self._lock:
                    e = self._entries.get(sid)
                    if e is not None and e.engine == src:
                        e.engine = dst
                        out["n_migrated"] += 1
                continue
            migrated = False
            if (
                src_pool is not None
                and dst_pool is not None
                and not src_pool.is_pinned(sid)
            ):
                got = src_pool.lookup(sid)
                row = src_pool.read_block(sid) if got is not None else None
                if row is not None:
                    n_tokens = got[1]
                    if getattr(dst_pool, "delta", False):
                        stored = (
                            dst_pool.write_back_stream(sid, row, n_tokens)
                            is not None
                        )
                    else:
                        stored = dst_pool.write_back(sid, row, n_tokens)
                    if stored:
                        with self._lock:
                            e = self._entries.get(sid)
                            if e is not None and e.engine == src:
                                e.engine = dst
                                migrated = True
                            if self.delta:
                                self._recount_locked()
                        if migrated:
                            src_pool.free(sid, reason="drain-migrate")
                            out["n_migrated"] += 1
                            out["bytes_migrated"] += int(row.nbytes)
                            events.append(
                                {
                                    "event": "cache_migrate",
                                    "session": sid,
                                    "src_engine": src,
                                    "dst_engine": dst,
                                    "bytes": int(row.nbytes),
                                }
                            )
                        else:
                            # The entry vanished mid-copy (TTL/evict
                            # raced): the dst copy is an orphan — free it.
                            dst_pool.free(sid, reason="migrate-raced")
            if not migrated:
                if self.invalidate(sid, reason=reason):
                    out["n_invalidated"] += 1
        self._flush(events)
        return out

    # -- internals ---------------------------------------------------------

    def _drop(self, session_id: str, entry: _Entry) -> None:
        # Caller holds the lock. In pages mode the entry's pages return
        # to its pool's free list (cache lock -> pool lock, the
        # documented order; the pool stamps its own page_free).
        self._entries.pop(session_id, None)
        self._bytes -= entry.nbytes
        if self.pools is not None:
            self.pools[entry.engine].free(session_id)
        if self.delta:
            # A dropped session may have been the charged owner of a
            # still-shared base, or an un-charged aliaser of one — the
            # per-entry nbytes cannot know which at drop time. Recount
            # from the pools' ACTUAL page occupancy instead.
            self._recount_locked()

    def _recount_locked(self) -> None:
        """DELTA mode: _bytes mirrors the pools' actual page occupancy
        (caller holds the cache lock; pool locks nest inside — the
        documented order)."""
        self._bytes = sum(p.bytes_in_use() for p in self.pools.values())
        self._peak_bytes = max(self._peak_bytes, self._bytes)

    def _flush(self, events: List[dict]) -> None:
        from glom_tpu.serve.events import emit_serve

        for rec in events:
            emit_serve(self.writer, rec)

    # -- observability -----------------------------------------------------

    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def record(self) -> dict:
        """The cache rollup the batcher nests under its summary record:
        counters plus live/peak bytes against the budget — the numbers
        the temporal bench's acceptance reads (`bytes_peak` must never
        exceed `budget_bytes`)."""
        with self._lock:
            rec = {
                "n_sessions": len(self._entries),
                "bytes_in_use": self._bytes,
                "bytes_peak": self._peak_bytes,
                "budget_bytes": self.budget_bytes,
                "ttl_s": self.ttl_s,
                "n_hits": self.n_hits,
                "n_misses": self.n_misses,
                "n_writes": self.n_writes,
                "n_evictions": self.n_evictions,
                "n_expirations": self.n_expirations,
                "n_invalidations": self.n_invalidations,
                "n_rejects": self.n_rejects,
            }
            if self.delta:
                # The cache-delta nest (docs/OBSERVABILITY.md): bytes and
                # chain length are COSTS the compare gate flattens as
                # serve_cache_delta.* rows; the atol is the explicit
                # tolerance stamp (0.0 = bitwise reconstruction).
                n_sessions = len(self._entries)
                agg: dict = {
                    "bytes_per_stream": (
                        round(self._bytes / n_sessions, 1)
                        if n_sessions
                        else None
                    ),
                }
                for p in self.pools.values():
                    sub = p.record().get("delta")
                    if not sub:
                        continue
                    agg.setdefault(
                        "delta_page_atol", sub["delta_page_atol"]
                    )
                    agg.setdefault(
                        "delta_chain_cap", sub["delta_chain_cap"]
                    )
                    agg["delta_chain_len_max"] = max(
                        agg.get("delta_chain_len_max", 0),
                        sub["delta_chain_len_max"],
                    )
                    for k in (
                        "n_delta_writes", "n_delta_pages", "n_delta_empty",
                        "n_compactions", "n_compact_deferred",
                        "n_base_shares",
                    ):
                        agg[k] = agg.get(k, 0) + sub[k]
                rec["delta"] = agg
            return rec


def resolve_column_cache(scfg, *, writer=None, pools=None) -> Optional[ColumnCache]:
    """The one config -> cache resolution: `column_cache_bytes > 0`
    builds the cache with the configured TTL, 0 disables streaming
    warm-start entirely (every request cold-starts — the pre-PR 8
    contract). `pools` (engine name -> PagedColumnPool, resolved by the
    batcher from the engines' page pools) switches the cache to PAGES
    mode: entries are page-table references and the warm path is
    device-resident (docs/SERVING.md, "Paged column memory")."""
    if getattr(scfg, "column_cache_bytes", 0) <= 0:
        return None
    return ColumnCache(
        scfg.column_cache_bytes,
        ttl_s=scfg.column_cache_ttl_s,
        writer=writer,
        pools=pools or None,
    )
