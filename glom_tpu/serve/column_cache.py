"""Session-keyed warm-start column cache: carry converged columns across
temporal frames.

GLOM's "islands of agreement" persist across the frames of a stream — a
request that starts from the PREVIOUS frame's converged column state is
already sitting near the consensus attractor, so the `iters="auto"` exit
fires in a fraction of the cold-start budget. This module is the O(1)
state-reuse pattern from the compiler-first autoregressive-caching
literature (PAPERS.md) applied to consensus state: the cached unit is one
session's `[n, L, d]` column tensor, written back after every resolved
request that carries a `session_id` and read at the NEXT dispatch as the
warm `levels0` init (the engine's existing warm-signature machinery — no
new compiled programs).

Residency discipline:

  * PRICED — every entry costs `column_state_bytes(cfg, scfg)` of the
    serving replica's HBM while a warm dispatch stages it (the same
    analytic live-bytes accounting utils/metrics.py prices train state
    with); the cache holds the HOST copy (device buffers are donated per
    dispatch and cannot be retained), but the budget is an HBM budget:
    entries beyond `ServeConfig.column_cache_bytes` evict LRU-first, and
    total resident bytes NEVER exceed the budget — an entry larger than
    the whole budget is rejected outright, not "temporarily" overcommitted;
  * TTL — a stream that went quiet is stale state, not warmth:
    `column_cache_ttl_s` expires an entry at lookup time (a hit on an
    expired entry is a MISS plus an eviction, stamped as such);
  * INVALIDATED on engine death/failover — entries are tagged with the
    engine that produced them, and the batcher drops an engine's entries
    the moment a dispatch on it fails (`invalidate_engine`), so a stale
    or dead-engine entry can never warm-start a request;
  * OBSERVED — hits/misses/evictions/expirations/invalidations and the
    live byte count are counters on `record()` (rolled into the batcher's
    summary), and every eviction/expiry/invalidation is a stamped "serve"
    event through the usual writer-else-flight delivery.

Thread-safe: lookups run on the batcher's per-engine worker threads while
stores/invalidations run on workers and the caller; one lock guards the
LRU map and every counter (events are emitted OUTSIDE the lock — the
writer may block on IO).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional

import numpy as np


def column_state_bytes(cfg, scfg) -> int:
    """The live-bytes price of ONE session's cached column state: the
    `[num_patches, levels, dim]` tensor in the serving compute dtype —
    the same analytic form the HBM accounting prices the warm `levels0`
    staging buffer with. This is what `ServeConfig.column_cache_bytes`
    is divided by when sizing a deployment (docs/SERVING.md,
    "Streaming")."""
    itemsize = 2 if scfg.compute_dtype == "bfloat16" else 4
    return cfg.num_patches * cfg.levels * cfg.dim * itemsize


class _Entry:
    __slots__ = ("levels", "nbytes", "engine", "t_write")

    def __init__(self, levels: np.ndarray, engine: str, t_write: float):
        self.levels = levels
        self.nbytes = int(levels.nbytes)
        self.engine = engine
        self.t_write = t_write


class ColumnCache:
    """LRU column-state cache keyed by session id, bounded in bytes.

    `budget_bytes` is the hard residency ceiling (HBM-priced via
    column_state_bytes); `ttl_s=None` disables expiry. The clock is
    injectable so TTL tests never sleep."""

    def __init__(
        self,
        budget_bytes: int,
        *,
        ttl_s: Optional[float] = None,
        writer=None,
        clock=time.monotonic,
    ):
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes {budget_bytes} must be >= 1")
        if ttl_s is not None and ttl_s <= 0:
            raise ValueError(f"ttl_s {ttl_s} must be > 0 or None")
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = ttl_s
        self.writer = writer
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._peak_bytes = 0
        self.n_hits = 0
        self.n_misses = 0
        self.n_writes = 0
        self.n_evictions = 0
        self.n_expirations = 0
        self.n_invalidations = 0
        self.n_rejects = 0

    # -- the request path --------------------------------------------------

    def lookup(self, session_id: str) -> Optional[np.ndarray]:
        """The session's cached column state (freshest-first LRU touch),
        or None on miss. An entry past its TTL is dropped HERE — an
        expired stream must never warm-start a request — and counts as
        one expiration plus the miss."""
        events: List[dict] = []
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                self.n_misses += 1
                return None
            if (
                self.ttl_s is not None
                and self._clock() - entry.t_write > self.ttl_s
            ):
                self._drop(session_id, entry)
                self.n_expirations += 1
                self.n_misses += 1
                events.append(
                    {
                        "event": "cache_expire",
                        "session": session_id,
                        "bytes": entry.nbytes,
                        "age_s": round(self._clock() - entry.t_write, 3),
                    }
                )
                levels = None
            else:
                self._entries.move_to_end(session_id)
                self.n_hits += 1
                levels = entry.levels
        self._flush(events)
        return levels

    def store(self, session_id: str, levels, *, engine: str) -> bool:
        """Write one resolved request's converged columns back under its
        session key (the warm init for the stream's NEXT frame), evicting
        LRU entries until the byte budget holds. Returns False when the
        entry alone exceeds the whole budget (rejected, stamped — the
        budget is a ceiling, never overcommitted)."""
        levels = np.asarray(levels)
        now = self._clock()
        events: List[dict] = []
        with self._lock:
            if int(levels.nbytes) > self.budget_bytes:
                self.n_rejects += 1
                events.append(
                    {
                        "event": "cache_reject",
                        "session": session_id,
                        "bytes": int(levels.nbytes),
                        "budget_bytes": self.budget_bytes,
                    }
                )
                stored = False
            else:
                old = self._entries.pop(session_id, None)
                if old is not None:
                    self._bytes -= old.nbytes
                entry = _Entry(levels, engine, now)
                self._entries[session_id] = entry
                self._bytes += entry.nbytes
                self.n_writes += 1
                while self._bytes > self.budget_bytes:
                    victim_id, victim = next(iter(self._entries.items()))
                    self._drop(victim_id, victim)
                    self.n_evictions += 1
                    events.append(
                        {
                            "event": "cache_evict",
                            "session": victim_id,
                            "bytes": victim.nbytes,
                            "bytes_in_use": self._bytes,
                            "budget_bytes": self.budget_bytes,
                        }
                    )
                self._peak_bytes = max(self._peak_bytes, self._bytes)
                stored = True
        self._flush(events)
        return stored

    # -- invalidation ------------------------------------------------------

    def invalidate(self, session_id: str, *, reason: str = "explicit") -> bool:
        """Drop one session's entry (stream ended, client reset)."""
        events: List[dict] = []
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                return False
            self._drop(session_id, entry)
            self.n_invalidations += 1
            events.append(
                {
                    "event": "cache_invalidate",
                    "session": session_id,
                    "reason": reason,
                    "bytes": entry.nbytes,
                }
            )
        self._flush(events)
        return True

    def invalidate_engine(self, engine: str, *, reason: str = "engine-failover") -> int:
        """Drop EVERY entry the named engine wrote — called by the
        batcher on a dispatch failure / engine death, so state produced
        near the failure can never warm-start a request. Returns how many
        entries were dropped."""
        events: List[dict] = []
        with self._lock:
            victims = [
                (sid, e) for sid, e in self._entries.items()
                if e.engine == engine
            ]
            for sid, entry in victims:
                self._drop(sid, entry)
                self.n_invalidations += 1
            if victims:
                events.append(
                    {
                        "event": "cache_invalidate",
                        "engine": engine,
                        "reason": reason,
                        "n_entries": len(victims),
                        "bytes": sum(e.nbytes for _, e in victims),
                    }
                )
        self._flush(events)
        return len(victims)

    # -- internals ---------------------------------------------------------

    def _drop(self, session_id: str, entry: _Entry) -> None:
        # Caller holds the lock.
        self._entries.pop(session_id, None)
        self._bytes -= entry.nbytes

    def _flush(self, events: List[dict]) -> None:
        from glom_tpu.serve.events import emit_serve

        for rec in events:
            emit_serve(self.writer, rec)

    # -- observability -----------------------------------------------------

    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def record(self) -> dict:
        """The cache rollup the batcher nests under its summary record:
        counters plus live/peak bytes against the budget — the numbers
        the temporal bench's acceptance reads (`bytes_peak` must never
        exceed `budget_bytes`)."""
        with self._lock:
            return {
                "n_sessions": len(self._entries),
                "bytes_in_use": self._bytes,
                "bytes_peak": self._peak_bytes,
                "budget_bytes": self.budget_bytes,
                "ttl_s": self.ttl_s,
                "n_hits": self.n_hits,
                "n_misses": self.n_misses,
                "n_writes": self.n_writes,
                "n_evictions": self.n_evictions,
                "n_expirations": self.n_expirations,
                "n_invalidations": self.n_invalidations,
                "n_rejects": self.n_rejects,
            }


def resolve_column_cache(scfg, *, writer=None) -> Optional[ColumnCache]:
    """The one config -> cache resolution: `column_cache_bytes > 0`
    builds the cache with the configured TTL, 0 disables streaming
    warm-start entirely (every request cold-starts — the pre-PR 8
    contract)."""
    if getattr(scfg, "column_cache_bytes", 0) <= 0:
        return None
    return ColumnCache(
        scfg.column_cache_bytes,
        ttl_s=scfg.column_cache_ttl_s,
        writer=writer,
    )
