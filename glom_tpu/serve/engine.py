"""The inference engine: params + one AOT-compiled forward per signature.

The trainer's throughput discipline (compile once, static shapes, donated
buffers) applied to serving. `Glom.__call__` jit-compiles on FIRST call —
fine for a notebook, a multi-second latency cliff for the first user to hit
a fresh shape in production. The engine inverts that:

  * every (bucket batch, iters route) signature is AOT-compiled — lowered
    and compiled EXPLICITLY via jax.jit(...).lower(...).compile() from
    abstract shapes, no dummy batch materialized — either eagerly by
    `warmup()` before traffic or lazily on first miss (which emits a
    "serve" warmup event either way, so a mid-traffic compile is always
    attributable in the stream);
  * compiled programs are memoized by signature for the engine's lifetime;
    the batcher only ever dispatches bucket shapes, so steady-state traffic
    never compiles;
  * the input buffer is donated on TPU (ServeConfig.donate=None resolves
    by platform) so XLA reuses the padded batch's HBM for outputs;
  * every forward returns (levels, iters_run): the fixed route stamps its
    constant, the "auto" route (serve/early_exit) returns the actual
    iteration count — the consensus early-exit win lands directly in the
    latency records.

Latency accounting rides telemetry/sinks.StepTimeStats per signature
(compile split out, p50/p95/p99/max), drained by `stats_records()` into
schema-v3 "serve" events.
"""

from __future__ import annotations

import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from glom_tpu.models.core import GlomParams, glom_forward, init_glom
from glom_tpu.serve.early_exit import glom_forward_auto
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.sinks import StepTimeStats
from glom_tpu.utils.config import GlomConfig, ServeConfig


class ServeResult(NamedTuple):
    """One dispatched batch's outcome. `levels` is the full padded
    [bucket, n, L, d] state (callers slice their valid rows); `iters_run`
    is a host int (the auto route's early-exit count, or the fixed
    budget); `latency_s` is dispatch-to-fetch wall time for the batch."""

    levels: jax.Array
    iters_run: int
    latency_s: float
    bucket: int
    compiled: bool  # True when this call paid the signature's compile


def _resolve_donate(donate: Optional[bool]) -> bool:
    if donate is not None:
        return donate
    return jax.devices()[0].platform == "tpu"


class InferenceEngine:
    """Owns params + memoized AOT-compiled forwards per bucket signature.

    The engine is the device-side half of the serving stack (the host-side
    half is serve/batcher.DynamicBatcher, which owns admission and
    padding). It is thread-compatible the way jax itself is: compiled
    executables may be CALLED from any thread; `warmup`/first-miss
    compilation is serialized by the GIL + dict memoization.
    """

    def __init__(
        self,
        cfg: GlomConfig,
        scfg: Optional[ServeConfig] = None,
        *,
        params: Optional[GlomParams] = None,
        key: Optional[jax.Array] = None,
        writer=None,
        retry=None,
        fault_hook=None,
    ):
        self.cfg = cfg
        self.scfg = scfg = scfg if scfg is not None else ServeConfig()
        if params is None:
            key = key if key is not None else jax.random.PRNGKey(0)
            params = init_glom(key, cfg)
        self.params = params
        self.writer = writer
        self._donate = _resolve_donate(scfg.donate)
        self._compute_dtype = (
            jnp.bfloat16 if scfg.compute_dtype == "bfloat16" else None
        )
        self._compiled: Dict[Tuple, object] = {}
        self._stats: Dict[Tuple, StepTimeStats] = {}
        # Transient-dispatch retry (glom_tpu/resilience/retry.py): None
        # resolves from the config (scfg.dispatch_retries; 0 disables).
        # The policy is watchdog-aware — a FLAPPING backend retries (the
        # gap closes), a DOWN backend fails fast into the shed path.
        if retry is None and scfg.dispatch_retries > 0:
            from glom_tpu.resilience.retry import RetryPolicy

            retry = RetryPolicy(
                retries=scfg.dispatch_retries,
                backoff_s=scfg.retry_backoff_ms / 1e3,
                writer=writer,
                site="engine-dispatch",
            )
        self.retry = retry
        # Chaos seam (glom_tpu/resilience/faults.dispatch_fault): called
        # once per dispatch ATTEMPT with {bucket, n_valid, attempt}; a
        # raise here is exactly a transient backend failure as far as the
        # retry policy and the batcher are concerned. None in production.
        self._fault_hook = fault_hook

    # -- signatures --------------------------------------------------------

    @property
    def iters_key(self):
        """The route component of every signature: "auto" or the resolved
        fixed iteration count."""
        if self.scfg.iters == "auto":
            return "auto"
        return (
            self.scfg.iters
            if self.scfg.iters is not None
            else self.cfg.default_iters
        )

    def pick_bucket(self, n: int) -> int:
        """Smallest precompile bucket admitting n requests. n above the
        largest bucket is the BATCHER's invariant to maintain (it never
        gathers more than max_batch <= max bucket); a direct caller gets
        the loud error."""
        if n < 1:
            raise ValueError(f"n={n} must be >= 1")
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"n={n} exceeds the largest bucket {max(self.scfg.buckets)}"
        )

    def signature(self, bucket: int, iters_override: Optional[int] = None) -> Tuple:
        route = iters_override if iters_override is not None else self.iters_key
        return (bucket, route, self.scfg.use_pallas)

    # -- compilation -------------------------------------------------------

    def _build_fn(self, bucket: int, iters_override: Optional[int] = None):
        """The pure forward for one bucket: (params, img [bucket,c,H,W],
        mask [bucket]) -> (levels [bucket,n,L,d], iters_run int32). The
        mask only matters on the auto route (pad rows must not vote on the
        early-exit witness); the fixed route carries it for a uniform
        calling convention.

        iters_override (the degradation ladder's capped_iters rung) pins
        a FIXED budget regardless of the configured route — a degraded
        dispatch costs a bounded, smaller iteration count, compiled and
        memoized as its own signature like any bucket."""
        cfg, scfg = self.cfg, self.scfg
        compute_dtype = self._compute_dtype

        if iters_override is None and self.iters_key == "auto":
            max_iters = (
                scfg.max_auto_iters
                if scfg.max_auto_iters is not None
                else cfg.default_iters
            )

            def fn(params, img, mask):
                final, iters_run, _ = glom_forward_auto(
                    params, img, cfg,
                    max_iters=max_iters,
                    threshold=scfg.exit_threshold,
                    min_iters=scfg.min_iters,
                    valid_mask=mask,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                return final, iters_run

        else:
            iters = (
                iters_override if iters_override is not None else self.iters_key
            )

            def fn(params, img, mask):
                del mask  # pad rows are harmless on the fixed route
                final = glom_forward(
                    params, img, cfg, iters=iters,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                return final, jnp.int32(iters)

        return fn

    def _compile(self, bucket: int, iters_override: Optional[int] = None):
        """AOT-compile one bucket signature from abstract shapes and emit
        the "serve" warmup event (compile seconds attributed per bucket)."""
        sig = self.signature(bucket, iters_override)
        if sig in self._compiled:
            return self._compiled[sig]
        cfg = self.cfg
        img_abs = jax.ShapeDtypeStruct(
            (bucket, cfg.channels, cfg.image_size, cfg.image_size), jnp.float32
        )
        mask_abs = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        params_abs = jax.tree_util.tree_map(
            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), self.params
        )
        donate = (1,) if self._donate else ()
        t0 = time.perf_counter()
        compiled = (
            jax.jit(self._build_fn(bucket, iters_override), donate_argnums=donate)
            .lower(params_abs, img_abs, mask_abs)
            .compile()
        )
        dt = time.perf_counter() - t0
        self._compiled[sig] = compiled
        self._stats.setdefault(sig, StepTimeStats()).observe(dt, is_compile=True)
        self._emit(
            {
                "event": "warmup",
                "bucket": bucket,
                "iters": sig[1],
                "degraded": iters_override is not None,
                "use_pallas": self.scfg.use_pallas,
                "compile_time_s": round(dt, 4),
            }
        )
        return compiled

    def warmup(
        self,
        buckets: Optional[Tuple[int, ...]] = None,
        *,
        iters_override: Optional[int] = None,
    ) -> dict:
        """Precompile every bucket signature BEFORE traffic. Returns
        {bucket: compile_seconds}; already-compiled signatures are free.
        Call a second time with iters_override=<degraded budget> to also
        pre-warm the ladder's capped_iters route (otherwise the first
        degraded dispatch pays an attributable mid-traffic compile)."""
        out = {}
        for b in buckets if buckets is not None else self.scfg.buckets:
            sig = self.signature(b, iters_override)
            already = sig in self._compiled
            t0 = time.perf_counter()
            self._compile(b, iters_override)
            out[b] = 0.0 if already else time.perf_counter() - t0
        return out

    # -- dispatch ----------------------------------------------------------

    def infer(
        self,
        imgs,
        n_valid: Optional[int] = None,
        *,
        iters_override: Optional[int] = None,
    ) -> ServeResult:
        """Run one padded batch. `imgs` is [b, c, H, W] (numpy or jax) with
        b equal to a bucket size — callers that batch themselves pass an
        exact bucket; the DynamicBatcher always does. `n_valid` marks how
        many leading rows are real requests (default: all).

        iters_override pins a fixed iteration budget for THIS dispatch
        (the degradation ladder's capped_iters rung); None runs the
        configured route. Transient dispatch failures retry per the
        engine's RetryPolicy — a failed attempt against an up-or-flapping
        backend backs off and re-dispatches from a FRESH input buffer
        (donation invalidates the old one), while a down backend raises
        straight into the batcher's shed path."""
        if iters_override is not None and (
            not isinstance(iters_override, int) or iters_override < 1
        ):
            raise ValueError(
                f"iters_override={iters_override!r}: an int >= 1 or None"
            )
        b = np.shape(imgs)[0]
        if b not in self.scfg.buckets:
            raise ValueError(
                f"batch {b} is not a bucket shape {self.scfg.buckets}; pad "
                "to a bucket (DynamicBatcher does) or add the bucket"
            )
        n_valid = b if n_valid is None else n_valid
        if not 1 <= n_valid <= b:
            raise ValueError(f"n_valid={n_valid} outside 1..{b}")
        if self._donate:
            # Every ATTEMPT needs a fresh device buffer: the compiled call
            # donates its input, so a retry after a failed dispatch must
            # never reuse a possibly-invalidated array. Hold the source on
            # the host (numpy transfers copy; a caller-held jax array is
            # deep-copied per attempt).
            src = imgs if isinstance(imgs, jax.Array) else np.asarray(
                imgs, np.float32
            )
            if isinstance(src, jax.Array):
                make_input = lambda: jnp.array(src, jnp.float32, copy=True)
            else:
                make_input = lambda: jnp.asarray(src, jnp.float32)
        else:
            dev = jnp.asarray(imgs, jnp.float32)
            make_input = lambda: dev
        mask = jnp.arange(b) < n_valid
        sig = self.signature(b, iters_override)
        compiled_before = sig in self._compiled
        fn = self._compile(b, iters_override)
        stats = self._stats.setdefault(sig, StepTimeStats())
        attempts = [0]

        def attempt():
            attempts[0] += 1
            if self._fault_hook is not None:
                self._fault_hook(
                    {"bucket": b, "n_valid": n_valid, "attempt": attempts[0]}
                )
            levels, iters_run = fn(self.params, make_input(), mask)
            iters_host = int(jax.device_get(iters_run))  # syncs: serving
            # is request/response — the caller needs the answer now, and
            # the fetch IS the latency being measured.
            levels.block_until_ready()
            return levels, iters_host

        t0 = time.perf_counter()
        if self.retry is not None:
            levels, iters_host = self.retry.run(
                attempt, bucket=b, n_valid=n_valid
            )
        else:
            levels, iters_host = attempt()
        dt = time.perf_counter() - t0
        stats.observe(dt, is_compile=False)
        return ServeResult(
            levels=levels,
            iters_run=iters_host,
            latency_s=dt,
            bucket=b,
            compiled=not compiled_before,
        )

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        from glom_tpu.serve.events import emit_serve

        emit_serve(self.writer, rec)

    def stats_records(self) -> list:
        """One stamped "serve" event per compiled signature with the
        per-bucket latency histogram (p50/p95/p99/max, compile split)."""
        out = []
        for (bucket, iters_key, pallas), stats in sorted(
            self._stats.items(), key=lambda kv: str(kv[0])
        ):
            out.append(
                schema.stamp(
                    {
                        "event": "bucket_stats",
                        "bucket": bucket,
                        "iters": iters_key,
                        "use_pallas": pallas,
                        **stats.summary(),
                    },
                    kind="serve",
                )
            )
        return out
