"""The inference engine: params + one AOT-compiled forward per signature.

The trainer's throughput discipline (compile once, static shapes, donated
buffers) applied to serving. `Glom.__call__` jit-compiles on FIRST call —
fine for a notebook, a multi-second latency cliff for the first user to hit
a fresh shape in production. The engine inverts that:

  * every (bucket batch, iters route, warm/cold) signature is AOT-compiled
    — lowered and compiled EXPLICITLY via jax.jit(...).lower(...).compile()
    from abstract shapes, no dummy batch materialized — either eagerly by
    `warmup()` before traffic or lazily on first miss (which emits a
    "serve" warmup event either way, so a mid-traffic compile is always
    attributable in the stream);
  * compiled programs are memoized by signature for the engine's lifetime;
    the batcher only ever dispatches bucket shapes, so steady-state traffic
    never compiles;
  * the input buffers (image batch, and the warm levels carry on
    continuation dispatches) are donated on TPU (ServeConfig.donate=None
    resolves by platform) so XLA reuses the padded batch's HBM for outputs;
  * every forward returns (levels, iters_run, row_converged, row_iters):
    the fixed route stamps its constant (all rows "converged" by fiat),
    the "auto" route (serve/early_exit.glom_forward_tiered) returns the
    actual executed count plus PER-ROW convergence — the two-tier early
    exit's raw material (docs/SERVING.md, "Continuation queue").

Sharded route (parallel/serve_mesh.py): when ServeConfig.mesh_data/.mesh_seq
describe a mesh, every signature compiles the manual shard_map forward over
('data', 'seq') instead — same buckets, same warmup, same donation, and the
compile-time counting trace records the per-dispatch collective wire bytes
(telemetry/counters.py) onto the signature's stats record.

Latency accounting rides telemetry/sinks.StepTimeStats per signature
(compile split out, p50/p95/p99/max), drained by `stats_records()` into
schema "serve" events.
"""

from __future__ import annotations

import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from glom_tpu.models.core import GlomParams, glom_forward, init_glom
from glom_tpu.serve.early_exit import glom_forward_tiered
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.sinks import StepTimeStats
from glom_tpu.utils.config import GlomConfig, ServeConfig


class ServeResult(NamedTuple):
    """One dispatched batch's outcome. `levels` is the full padded
    [bucket, n, L, d] state (callers slice their valid rows); `iters_run`
    is a host int (the auto route's early-exit count, or the fixed
    budget); `latency_s` is dispatch-to-fetch wall time for the batch.
    `row_converged`/`row_iters` are the PER-ROW tiered-exit outcome
    ([bucket] host arrays; fixed-route dispatches mark every row
    converged — there are no stragglers without a witness)."""

    levels: jax.Array
    iters_run: int
    latency_s: float
    bucket: int
    compiled: bool  # True when this call paid the signature's compile
    row_converged: Optional[np.ndarray] = None
    row_iters: Optional[np.ndarray] = None


def _resolve_donate(donate: Optional[bool]) -> bool:
    if donate is not None:
        return donate
    return jax.devices()[0].platform == "tpu"


class InferenceEngine:
    """Owns params + memoized AOT-compiled forwards per bucket signature.

    The engine is the device-side half of the serving stack (the host-side
    half is serve/batcher.DynamicBatcher, which owns admission, padding,
    and the continuation queue). It is thread-compatible the way jax
    itself is: compiled executables may be CALLED from any thread;
    `warmup`/first-miss compilation is serialized by the GIL + dict
    memoization. `name` labels this engine's records in multi-engine
    fan-out deployments (one engine per replica behind one batcher).
    """

    def __init__(
        self,
        cfg: GlomConfig,
        scfg: Optional[ServeConfig] = None,
        *,
        params: Optional[GlomParams] = None,
        key: Optional[jax.Array] = None,
        writer=None,
        retry=None,
        fault_hook=None,
        mesh=None,
        name: str = "engine0",
    ):
        self.cfg = cfg
        self.scfg = scfg = scfg if scfg is not None else ServeConfig()
        self.name = name
        if params is None:
            key = key if key is not None else jax.random.PRNGKey(0)
            params = init_glom(key, cfg)
        self.params = params
        self.writer = writer
        self._donate = _resolve_donate(scfg.donate)
        self._compute_dtype = (
            jnp.bfloat16 if scfg.compute_dtype == "bfloat16" else None
        )
        # Serve mesh: an explicit mesh wins; else resolve from the config
        # (mesh axes of 1 mean the single-device route).
        if mesh is None and (scfg.mesh_data > 1 or scfg.mesh_seq > 1):
            from glom_tpu.parallel.serve_mesh import make_serve_mesh

            mesh = make_serve_mesh(scfg)
        self.mesh = mesh
        if mesh is not None and cfg.num_patches % scfg.mesh_seq != 0:
            raise ValueError(
                f"patches {cfg.num_patches} not divisible by "
                f"mesh_seq={scfg.mesh_seq}"
            )
        self._compiled: Dict[Tuple, object] = {}
        self._cold_levels: Optional[np.ndarray] = None
        self._stats: Dict[Tuple, StepTimeStats] = {}
        self._comm: Dict[Tuple, dict] = {}  # sharded route: counted wire bytes
        self._shardings: Dict[bool, Tuple] = {}  # warm -> (in_sh, out_sh)
        # Transient-dispatch retry (glom_tpu/resilience/retry.py): None
        # resolves from the config (scfg.dispatch_retries; 0 disables).
        # The policy is watchdog-aware — a FLAPPING backend retries (the
        # gap closes), a DOWN backend fails fast into the shed path.
        if retry is None and scfg.dispatch_retries > 0:
            from glom_tpu.resilience.retry import RetryPolicy

            retry = RetryPolicy(
                retries=scfg.dispatch_retries,
                backoff_s=scfg.retry_backoff_ms / 1e3,
                writer=writer,
                site=f"{name}-dispatch",
            )
        self.retry = retry
        # Chaos seam (glom_tpu/resilience/faults.dispatch_fault): called
        # once per dispatch ATTEMPT with {bucket, n_valid, attempt}; a
        # raise here is exactly a transient backend failure as far as the
        # retry policy and the batcher are concerned. None in production.
        self._fault_hook = fault_hook

    # -- signatures --------------------------------------------------------

    @property
    def iters_key(self):
        """The route component of every signature: "auto" or the resolved
        fixed iteration count."""
        if self.scfg.iters == "auto":
            return "auto"
        return (
            self.scfg.iters
            if self.scfg.iters is not None
            else self.cfg.default_iters
        )

    @property
    def auto_budget(self) -> int:
        """The auto route's full iteration budget — the per-REQUEST cap
        the two-tier continuation path never exceeds (a straggler's
        continuation runs the REMAINING budget, so initial + continuation
        iterations total at most this)."""
        return (
            self.scfg.max_auto_iters
            if self.scfg.max_auto_iters is not None
            else self.cfg.default_iters
        )

    def cold_levels(self) -> np.ndarray:
        """The cold-start column state for ONE row — `init_levels`
        broadcast to [n_patches, L, d] in the serving dtype, exactly the
        init the forward builds when no `levels0` is carried. The batcher
        uses it to fold COLD rows into a warm-signature dispatch (mixed
        warm/cold buckets): a cold row whose levels0 is this state lands
        on bitwise the same columns as a cold dispatch, because the
        forward's own init IS this broadcast (locked by tests). Host
        array, memoized (read-only — callers copy into their staging
        buffer)."""
        if self._cold_levels is None:
            lv_dtype = (
                self._compute_dtype if self._compute_dtype is not None
                else np.float32
            )
            init = np.asarray(self.params.init_levels, lv_dtype)  # [L, d]
            self._cold_levels = np.ascontiguousarray(
                np.broadcast_to(init[None], (self.cfg.num_patches, *init.shape))
            )
        return self._cold_levels

    def pick_bucket(self, n: int) -> int:
        """Smallest precompile bucket admitting n requests. n above the
        largest bucket is the BATCHER's invariant to maintain (it never
        gathers more than max_batch <= max bucket); a direct caller gets
        the loud error."""
        if n < 1:
            raise ValueError(f"n={n} must be >= 1")
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"n={n} exceeds the largest bucket {max(self.scfg.buckets)}"
        )

    def signature(
        self,
        bucket: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm: bool = False,
    ) -> Tuple:
        if iters_override is not None:
            route = iters_override
        elif auto_budget is not None and self.iters_key == "auto":
            route = f"auto:{auto_budget}"
        else:
            route = self.iters_key
        return (bucket, route, self.scfg.use_pallas, warm)

    # -- compilation -------------------------------------------------------

    def _build_fn(
        self,
        bucket: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm: bool = False,
    ):
        """The pure forward for one signature: (params, img [bucket,c,H,W],
        mask [bucket][, levels0 [bucket,n,L,d]]) -> (levels
        [bucket,n,L,d], iters_run int32, row_converged [bucket] bool,
        row_iters [bucket] int32). The mask only matters on the auto route
        (pad rows must not vote on the early-exit witness or the quorum);
        the fixed route carries it for a uniform calling convention.

        iters_override (the degradation ladder's capped_iters rung) pins
        a FIXED budget regardless of the configured route; auto_budget
        caps the auto route's max_iters (a continuation dispatch runs its
        stragglers' REMAINING budget); warm compiles the variant taking a
        carried-in levels state. Each is its own memoized signature."""
        cfg, scfg = self.cfg, self.scfg
        compute_dtype = self._compute_dtype
        auto = iters_override is None and self.iters_key == "auto"
        if auto:
            max_iters = (
                auto_budget if auto_budget is not None else self.auto_budget
            )
        else:
            max_iters = (
                iters_override if iters_override is not None else self.iters_key
            )

        if self.mesh is not None:
            from glom_tpu.parallel.serve_mesh import make_serve_forward

            return make_serve_forward(
                self.mesh, cfg,
                route="auto" if auto else max_iters,
                max_iters=max_iters if auto else None,
                threshold=scfg.exit_threshold,
                min_iters=min(scfg.min_iters, max_iters),
                quorum=scfg.exit_quorum,
                compute_dtype=compute_dtype,
                use_pallas=scfg.use_pallas,
                warm=warm,
            )

        if auto:

            def fn(params, img, mask, levels0=None):
                res = glom_forward_tiered(
                    params, img, cfg,
                    max_iters=max_iters,
                    threshold=scfg.exit_threshold,
                    min_iters=min(scfg.min_iters, max_iters),
                    quorum=scfg.exit_quorum,
                    levels=levels0,
                    valid_mask=mask,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                return res.levels, res.iters_run, res.row_converged, res.row_iters

        else:
            iters = max_iters

            def fn(params, img, mask, levels0=None):
                del mask  # pad rows are harmless on the fixed route
                final = glom_forward(
                    params, img, cfg, iters=iters,
                    levels=levels0,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                b = final.shape[0]
                return (
                    final,
                    jnp.int32(iters),
                    jnp.ones((b,), bool),
                    jnp.full((b,), iters, jnp.int32),
                )

        if warm:
            return fn
        return lambda params, img, mask: fn(params, img, mask)

    def _compile(
        self,
        bucket: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm: bool = False,
    ):
        """AOT-compile one bucket signature from abstract shapes and emit
        the "serve" warmup event (compile seconds attributed per bucket).
        Sharded signatures additionally run the lowering inside a
        collective-counting context, so the per-dispatch wire bytes land
        on the signature's stats record (while-loop sites price the
        BUDGET — see parallel/serve_mesh.py)."""
        sig = self.signature(
            bucket, iters_override, auto_budget=auto_budget, warm=warm
        )
        if sig in self._compiled:
            return self._compiled[sig]
        cfg = self.cfg
        img_abs = jax.ShapeDtypeStruct(
            (bucket, cfg.channels, cfg.image_size, cfg.image_size), jnp.float32
        )
        mask_abs = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        params_abs = jax.tree_util.tree_map(
            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), self.params
        )
        lv_dtype = (
            self._compute_dtype if self._compute_dtype is not None
            else jnp.float32
        )
        lv_abs = jax.ShapeDtypeStruct(
            (bucket, cfg.num_patches, cfg.levels, cfg.dim), lv_dtype
        )
        abstract = (params_abs, img_abs, mask_abs) + ((lv_abs,) if warm else ())
        # Donate the image batch, and the warm levels carry with it.
        donate = ((1, 3) if warm else (1,)) if self._donate else ()
        fn = self._build_fn(
            bucket, iters_override, auto_budget=auto_budget, warm=warm
        )
        jit_kw = {"donate_argnums": donate}
        if self.mesh is not None:
            in_sh, out_sh = self._serve_shardings(warm)
            jit_kw.update(in_shardings=in_sh, out_shardings=out_sh)
        t0 = time.perf_counter()
        if self.mesh is not None:
            from glom_tpu.telemetry.counters import (
                CollectiveCounters,
                recording,
            )

            counters = CollectiveCounters()
            with recording(counters):
                lowered = jax.jit(fn, **jit_kw).lower(*abstract)
            self._comm[sig] = counters.totals()
        else:
            lowered = jax.jit(fn, **jit_kw).lower(*abstract)
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        self._compiled[sig] = compiled
        self._stats.setdefault(sig, StepTimeStats()).observe(dt, is_compile=True)
        self._emit(
            {
                "event": "warmup",
                "bucket": bucket,
                "iters": sig[1],
                "warm_state": warm,
                "degraded": iters_override is not None,
                "sharded": self.mesh is not None,
                "use_pallas": self.scfg.use_pallas,
                "compile_time_s": round(dt, 4),
            }
        )
        return compiled

    def warmup(
        self,
        buckets: Optional[Tuple[int, ...]] = None,
        *,
        iters_override: Optional[int] = None,
        warm: bool = False,
    ) -> dict:
        """Precompile every bucket signature BEFORE traffic. Returns
        {bucket: compile_seconds}; already-compiled signatures are free.
        Call again with iters_override=<degraded budget> to pre-warm the
        ladder's capped_iters route, or warm=True for the continuation
        path's warm-state shape (continuation dispatches at partial
        budgets still compile on first miss — each remaining budget is
        its own signature, attributable in the warmup stream)."""
        out = {}
        for b in buckets if buckets is not None else self.scfg.buckets:
            sig = self.signature(b, iters_override, warm=warm)
            already = sig in self._compiled
            t0 = time.perf_counter()
            self._compile(b, iters_override, warm=warm)
            out[b] = 0.0 if already else time.perf_counter() - t0
        return out

    # -- dispatch ----------------------------------------------------------

    def _serve_shardings(self, warm: bool) -> Tuple:
        """Memoized (in_shardings, out_shardings) for the sharded route —
        resolved once per (engine, warm) rather than per dispatch (the
        param tree_map is pure overhead in the request hot path)."""
        if warm not in self._shardings:
            from glom_tpu.parallel.serve_mesh import serve_shardings

            self._shardings[warm] = serve_shardings(
                self.mesh, self.params, warm=warm
            )
        return self._shardings[warm]

    def _device_input(self, src, sharding_spec=None):
        """One fresh device buffer per attempt (donation invalidates the
        previous one). On the sharded route the host array device_puts
        straight into its NamedSharding; single-device keeps the plain
        transfer."""
        if self.mesh is not None and sharding_spec is not None:
            return jax.device_put(np.asarray(src), sharding_spec)
        return jnp.asarray(src)

    def infer(
        self,
        imgs,
        n_valid: Optional[int] = None,
        *,
        iters_override: Optional[int] = None,
        levels0=None,
        auto_budget: Optional[int] = None,
    ) -> ServeResult:
        """Run one padded batch. `imgs` is [b, c, H, W] (numpy or jax) with
        b equal to a bucket size — callers that batch themselves pass an
        exact bucket; the DynamicBatcher always does. `n_valid` marks how
        many leading rows are real requests (default: all).

        iters_override pins a fixed iteration budget for THIS dispatch
        (the degradation ladder's capped_iters rung); None runs the
        configured route. levels0 [b, n, L, d] carries warm column state
        in (the continuation path), and auto_budget caps the auto route's
        max_iters to the stragglers' remaining budget. Transient dispatch
        failures retry per the engine's RetryPolicy — a failed attempt
        against an up-or-flapping backend backs off and re-dispatches from
        FRESH input buffers (donation invalidates the old ones), while a
        down backend raises straight into the batcher's shed path."""
        if iters_override is not None and (
            not isinstance(iters_override, int) or iters_override < 1
        ):
            raise ValueError(
                f"iters_override={iters_override!r}: an int >= 1 or None"
            )
        if auto_budget is not None:
            if not isinstance(auto_budget, int) or auto_budget < 1:
                raise ValueError(
                    f"auto_budget={auto_budget!r}: an int >= 1 or None"
                )
            if iters_override is not None:
                raise ValueError(
                    "auto_budget composes with the auto route only, not "
                    "with a fixed iters_override"
                )
        b = np.shape(imgs)[0]
        if b not in self.scfg.buckets:
            raise ValueError(
                f"batch {b} is not a bucket shape {self.scfg.buckets}; pad "
                "to a bucket (DynamicBatcher does) or add the bucket"
            )
        n_valid = b if n_valid is None else n_valid
        if not 1 <= n_valid <= b:
            raise ValueError(f"n_valid={n_valid} outside 1..{b}")
        warm = levels0 is not None
        if warm and np.shape(levels0)[0] != b:
            raise ValueError(
                f"levels0 batch {np.shape(levels0)[0]} != bucket {b}"
            )
        lv_dtype = (
            self._compute_dtype if self._compute_dtype is not None
            else np.float32
        )
        img_sh = mask_sh = lv_sh = None
        if self.mesh is not None:
            in_sh, _ = self._serve_shardings(warm)
            img_sh, mask_sh = in_sh[1], in_sh[2]
            lv_sh = in_sh[3] if warm else None
        if self._donate:
            # Every ATTEMPT needs fresh device buffers: the compiled call
            # donates its inputs, so a retry after a failed dispatch must
            # never reuse a possibly-invalidated array. Hold the sources
            # on the HOST (np.asarray of a caller-held jax array fetches a
            # copy, so the caller's buffer is never the donated one) and
            # re-transfer per attempt.
            src = np.asarray(imgs, np.float32)
            make_input = lambda: self._device_input(src, img_sh)
            lv_src = None if not warm else np.asarray(levels0, lv_dtype)
            make_levels = (
                None if not warm
                else (lambda: self._device_input(lv_src, lv_sh))
            )
        else:
            dev = self._device_input(np.asarray(imgs, np.float32), img_sh)
            make_input = lambda: dev
            if warm:
                lv_dev = self._device_input(
                    np.asarray(levels0, lv_dtype), lv_sh
                )
                make_levels = lambda: lv_dev
            else:
                make_levels = None
        mask_host = np.arange(b) < n_valid
        mask = (
            jax.device_put(mask_host, mask_sh)
            if mask_sh is not None
            else jnp.asarray(mask_host)
        )
        sig = self.signature(
            b, iters_override, auto_budget=auto_budget, warm=warm
        )
        compiled_before = sig in self._compiled
        fn = self._compile(
            b, iters_override, auto_budget=auto_budget, warm=warm
        )
        stats = self._stats.setdefault(sig, StepTimeStats())
        attempts = [0]

        def attempt():
            attempts[0] += 1
            if self._fault_hook is not None:
                self._fault_hook(
                    {"bucket": b, "n_valid": n_valid, "attempt": attempts[0]}
                )
            args = (self.params, make_input(), mask)
            if warm:
                args = args + (make_levels(),)
            levels, iters_run, conv, row_iters = fn(*args)
            iters_host = int(jax.device_get(iters_run))  # syncs: serving
            # is request/response — the caller needs the answer now, and
            # the fetch IS the latency being measured.
            levels.block_until_ready()
            return (
                levels,
                iters_host,
                np.asarray(jax.device_get(conv)),
                np.asarray(jax.device_get(row_iters)),
            )

        t0 = time.perf_counter()
        if self.retry is not None:
            out = self.retry.run(attempt, bucket=b, n_valid=n_valid)
        else:
            out = attempt()
        levels, iters_host, conv, row_iters = out
        dt = time.perf_counter() - t0
        stats.observe(dt, is_compile=False)
        return ServeResult(
            levels=levels,
            iters_run=iters_host,
            latency_s=dt,
            bucket=b,
            compiled=not compiled_before,
            row_converged=conv,
            row_iters=row_iters,
        )

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        from glom_tpu.serve.events import emit_serve

        emit_serve(self.writer, dict(rec, engine=self.name))

    def stats_records(self) -> list:
        """One stamped "serve" event per compiled signature with the
        per-bucket latency histogram (p50/p95/p99/max, compile split) and,
        on the sharded route, the counted per-dispatch collective wire
        bytes from the lowering trace."""
        out = []
        for sig, stats in sorted(
            self._stats.items(), key=lambda kv: str(kv[0])
        ):
            bucket, iters_key, pallas, warm = sig
            rec = {
                "event": "bucket_stats",
                "engine": self.name,
                "bucket": bucket,
                "iters": iters_key,
                "warm_state": warm,
                "use_pallas": pallas,
                **stats.summary(),
            }
            if sig in self._comm:
                rec.update(self._comm[sig])
            out.append(schema.stamp(rec, kind="serve"))
        return out
