"""The inference engine: params + one AOT-compiled forward per signature.

The trainer's throughput discipline (compile once, static shapes, donated
buffers) applied to serving. `Glom.__call__` jit-compiles on FIRST call —
fine for a notebook, a multi-second latency cliff for the first user to hit
a fresh shape in production. The engine inverts that:

  * every (bucket batch, iters route, warm/cold) signature is AOT-compiled
    — lowered and compiled EXPLICITLY via jax.jit(...).lower(...).compile()
    from abstract shapes, no dummy batch materialized — either eagerly by
    `warmup()` before traffic or lazily on first miss (which emits a
    "serve" warmup event either way, so a mid-traffic compile is always
    attributable in the stream);
  * compiled programs are memoized by signature for the engine's lifetime;
    the batcher only ever dispatches bucket shapes, so steady-state traffic
    never compiles;
  * the input buffers (image batch, and the warm levels carry on
    continuation dispatches) are donated on TPU (ServeConfig.donate=None
    resolves by platform) so XLA reuses the padded batch's HBM for outputs;
  * every forward returns (levels, iters_run, row_converged, row_iters):
    the fixed route stamps its constant (all rows "converged" by fiat),
    the "auto" route (serve/early_exit.glom_forward_tiered) returns the
    actual executed count plus PER-ROW convergence — the two-tier early
    exit's raw material (docs/SERVING.md, "Continuation queue").

Sharded route (parallel/serve_mesh.py): when ServeConfig.mesh_data/.mesh_seq
describe a mesh, every signature compiles the manual shard_map forward over
('data', 'seq') instead — same buckets, same warmup, same donation, and the
compile-time counting trace records the per-dispatch collective wire bytes
(telemetry/counters.py) onto the signature's stats record.

Latency accounting rides telemetry/sinks.StepTimeStats per signature
(compile split out, p50/p95/p99/max), drained by `stats_records()` into
schema "serve" events.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from glom_tpu.models.core import GlomParams, glom_forward, init_glom
from glom_tpu.serve.early_exit import glom_forward_tiered
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.sinks import StepTimeStats
from glom_tpu.utils.config import GlomConfig, ServeConfig


class ServeResult(NamedTuple):
    """One dispatched batch's outcome. `levels` is the full padded
    [bucket, n, L, d] state (callers slice their valid rows); `iters_run`
    is a host int (the auto route's early-exit count, or the fixed
    budget); `latency_s` is dispatch-to-fetch wall time for the batch.
    `row_converged`/`row_iters` are the PER-ROW tiered-exit outcome
    ([bucket] host arrays; fixed-route dispatches mark every row
    converged — there are no stragglers without a witness).
    `levels0_h2d_bytes` is what the dispatch UPLOADED of warm column
    state (host levels0 x attempts; 0 on the cold and PAGED routes — the
    zero the ragged bench gate asserts). `phases` is the engine-side
    latency decomposition when ServeConfig.phase_split is on
    ({"h2d_ms", "resolve_ms"} raw floats, summed across retry attempts;
    the batcher derives device_ms as the engine wall minus both and adds
    its own queue_wait/pack phases — docs/OBSERVABILITY.md, "Capacity
    observatory")."""

    levels: jax.Array
    iters_run: int
    latency_s: float
    bucket: int
    compiled: bool  # True when this call paid the signature's compile
    row_converged: Optional[np.ndarray] = None
    row_iters: Optional[np.ndarray] = None
    levels0_h2d_bytes: int = 0
    phases: Optional[dict] = None


class RaggedServeResult(NamedTuple):
    """One RAGGED dispatch's outcome. `levels` is the FLAT page-aligned
    [T, L, d] device state (row r's columns at [start_r, start_r +
    n_patches[r]) — serve/early_exit.ragged_row_layout); `pages` is the
    compiled page-count signature this dispatch rode."""

    levels: jax.Array
    iters_run: int
    latency_s: float
    pages: int
    compiled: bool
    row_converged: np.ndarray
    row_iters: np.ndarray
    levels0_h2d_bytes: int = 0
    phases: Optional[dict] = None


def _resolve_donate(donate: Optional[bool]) -> bool:
    if donate is not None:
        return donate
    return jax.devices()[0].platform == "tpu"


class InferenceEngine:
    """Owns params + memoized AOT-compiled forwards per bucket signature.

    The engine is the device-side half of the serving stack (the host-side
    half is serve/batcher.DynamicBatcher, which owns admission, padding,
    and the continuation queue). It is thread-compatible the way jax
    itself is: compiled executables may be CALLED from any thread;
    `warmup`/first-miss compilation is serialized by the GIL + dict
    memoization. `name` labels this engine's records in multi-engine
    fan-out deployments (one engine per replica behind one batcher).
    """

    def __init__(
        self,
        cfg: GlomConfig,
        scfg: Optional[ServeConfig] = None,
        *,
        params: Optional[GlomParams] = None,
        key: Optional[jax.Array] = None,
        writer=None,
        retry=None,
        fault_hook=None,
        mesh=None,
        name: str = "engine0",
    ):
        self.cfg = cfg
        self.scfg = scfg = scfg if scfg is not None else ServeConfig()
        self.name = name
        if params is None:
            key = key if key is not None else jax.random.PRNGKey(0)
            params = init_glom(key, cfg)
        self.params = params
        self.writer = writer
        self._donate = _resolve_donate(scfg.donate)
        self._compute_dtype = (
            jnp.bfloat16 if scfg.compute_dtype == "bfloat16" else None
        )
        # Serve mesh: an explicit mesh wins; else resolve from the config
        # (mesh axes of 1 mean the single-device route).
        if mesh is None and (scfg.mesh_data > 1 or scfg.mesh_seq > 1):
            from glom_tpu.parallel.serve_mesh import make_serve_mesh

            mesh = make_serve_mesh(scfg)
        self.mesh = mesh
        if mesh is not None and cfg.num_patches % scfg.mesh_seq != 0:
            raise ValueError(
                f"patches {cfg.num_patches} not divisible by "
                f"mesh_seq={scfg.mesh_seq}"
            )
        self._compiled: Dict[Tuple, object] = {}
        self._cold_levels: Optional[np.ndarray] = None
        self._stats: Dict[Tuple, StepTimeStats] = {}
        self._comm: Dict[Tuple, dict] = {}  # sharded route: counted wire bytes
        self._shardings: Dict = {}  # warm mode -> (in_sh, out_sh)
        # Per-collective wall-time (docs/OBSERVABILITY.md, "Capacity
        # observatory"): resolved like telemetry_level. Only the sharded
        # route has collectives — a single-device engine resolves any
        # configured mode to "off", loudly, so no record can claim a
        # timing harness with no sites to time. "full" brackets every
        # execution of every witness/gather site with io_callbacks
        # inserted at the AOT trace; "sampled" re-dispatches each site as
        # its own timed sub-graph every collective_timing_interval-th
        # dispatch (telemetry/comm_time.py).
        from glom_tpu.telemetry.counters import (
            CollectiveTimeLog,
            resolve_collective_timing,
        )

        if mesh is not None:
            self.collective_timing = resolve_collective_timing(
                scfg.collective_timing, supports_full=True
            )
        else:
            resolve_collective_timing(scfg.collective_timing)  # validate
            if scfg.collective_timing != "off":
                import warnings

                warnings.warn(
                    "collective_timing has no sites on a single-device "
                    "engine (no collectives) — resolving 'off'",
                    stacklevel=2,
                )
            self.collective_timing = "off"
        self._coll_log = (
            CollectiveTimeLog() if self.collective_timing == "full" else None
        )
        self._coll_sites: Dict[Tuple, dict] = {}  # (site, shape) -> site
        self._coll_sampler = None
        self._coll_samples: list = []  # sampled-mode stamped records
        self._coll_dispatches = 0
        self._coll_lock = threading.Lock()
        # Serializes the SAMPLING PASS itself (sub-graph compiles + timed
        # dispatches) separately from the cheap counter/buffer lock, so a
        # concurrent dispatch's tick never stalls behind another thread's
        # sample — the "cost lands on one dispatch in N" contract.
        self._coll_sample_lock = threading.Lock()
        # Host-side toggle for the latency decomposition's engine half
        # (the input sync + fetch attribution in infer): resolved from
        # the config, but a plain attribute so the phase-overhead A/B
        # can flip it per arm on SHARED engines without a recompile —
        # the split never touches the compiled program.
        self.phase_split = bool(getattr(scfg, "phase_split", True))
        # Paged column memory (serve/paged_columns.py): page_pool_pages
        # > 0 preallocates THIS engine's device page pool — warm column
        # state lives in HBM pages, assembled in-graph by a page-index
        # take (zero host->device levels0 bytes on the paged warm path).
        # On the sharded route the pool buffer shards its PAGE axis over
        # 'data' and the forward gathers it with a registered all_gather
        # (parallel/serve_mesh.py).
        from glom_tpu.serve.paged_columns import resolve_page_pool

        pool_sharding = None
        if mesh is not None and getattr(scfg, "page_pool_pages", 0) > 0:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            if scfg.page_pool_pages % scfg.mesh_data != 0:
                raise ValueError(
                    f"page_pool_pages {scfg.page_pool_pages} not divisible "
                    f"by mesh_data={scfg.mesh_data} (the pool's page axis "
                    "shards over 'data')"
                )
            pool_sharding = NamedSharding(mesh, P("data"))
        self.pool = resolve_page_pool(
            cfg, scfg, writer=writer, name=name, pool_sharding=pool_sharding
        )
        if getattr(scfg, "ragged", False):
            if mesh is not None:
                raise ValueError(
                    "ragged admission rides the single-device route only "
                    "(the sharded ragged gather is a follow-on; "
                    "docs/SERVING.md)"
                )
            if cfg.local_consensus_radius > 0:
                raise ValueError(
                    "ragged admission requires local_consensus_radius == 0"
                )
            from glom_tpu.serve.paged_columns import (
                pages_for_tokens,
                resolve_page_tokens,
            )

            ppr = pages_for_tokens(
                cfg.num_patches, resolve_page_tokens(cfg, scfg)
            )
            if scfg.ragged_pages and max(scfg.ragged_pages) < ppr:
                # Admission allows any row up to num_patches tokens —
                # a ladder that cannot hold one full-resolution row
                # would turn every such request into a dispatch-time
                # failure that reads as an ENGINE fault.
                raise ValueError(
                    f"ragged_pages top {max(scfg.ragged_pages)} is below "
                    f"one full-resolution row's {ppr} pages — every "
                    "full-size request would fail at dispatch"
                )
        # Host levels0 upload accounting (the PR 8 warm path's PCIe tax;
        # the paged route's reason to exist): total bytes of warm column
        # state this engine transferred host->device. The ragged bench
        # gate asserts this stays ZERO on the paged warm path.
        self.levels0_h2d_bytes_total = 0
        # Transient-dispatch retry (glom_tpu/resilience/retry.py): None
        # resolves from the config (scfg.dispatch_retries; 0 disables).
        # The policy is watchdog-aware — a FLAPPING backend retries (the
        # gap closes), a DOWN backend fails fast into the shed path.
        if retry is None and scfg.dispatch_retries > 0:
            from glom_tpu.resilience.retry import RetryPolicy

            retry = RetryPolicy(
                retries=scfg.dispatch_retries,
                backoff_s=scfg.retry_backoff_ms / 1e3,
                writer=writer,
                site=f"{name}-dispatch",
            )
        self.retry = retry
        # Chaos seam (glom_tpu/resilience/faults.dispatch_fault): called
        # once per dispatch ATTEMPT with {bucket, n_valid, attempt}; a
        # raise here is exactly a transient backend failure as far as the
        # retry policy and the batcher are concerned. None in production.
        self._fault_hook = fault_hook
        # Flipped by release() after a graceful drain: the engine is an
        # evidence husk — its device state is freed and it must never
        # serve again (the batcher already removed it from the fleet).
        self.released = False

    # -- signatures --------------------------------------------------------

    @property
    def iters_key(self):
        """The route component of every signature: "auto" or the resolved
        fixed iteration count."""
        if self.scfg.iters == "auto":
            return "auto"
        return (
            self.scfg.iters
            if self.scfg.iters is not None
            else self.cfg.default_iters
        )

    @property
    def auto_budget(self) -> int:
        """The auto route's full iteration budget — the per-REQUEST cap
        the two-tier continuation path never exceeds (a straggler's
        continuation runs the REMAINING budget, so initial + continuation
        iterations total at most this)."""
        return (
            self.scfg.max_auto_iters
            if self.scfg.max_auto_iters is not None
            else self.cfg.default_iters
        )

    def cold_levels(self) -> np.ndarray:
        """The cold-start column state for ONE row — `init_levels`
        broadcast to [n_patches, L, d] in the serving dtype, exactly the
        init the forward builds when no `levels0` is carried. The batcher
        uses it to fold COLD rows into a warm-signature dispatch (mixed
        warm/cold buckets): a cold row whose levels0 is this state lands
        on bitwise the same columns as a cold dispatch, because the
        forward's own init IS this broadcast (locked by tests). Host
        array, memoized (read-only — callers copy into their staging
        buffer)."""
        if self._cold_levels is None:
            lv_dtype = (
                self._compute_dtype if self._compute_dtype is not None
                else np.float32
            )
            init = np.asarray(self.params.init_levels, lv_dtype)  # [L, d]
            self._cold_levels = np.ascontiguousarray(
                np.broadcast_to(init[None], (self.cfg.num_patches, *init.shape))
            )
        return self._cold_levels

    def pick_bucket(self, n: int) -> int:
        """Smallest precompile bucket admitting n requests. n above the
        largest bucket is the BATCHER's invariant to maintain (it never
        gathers more than max_batch <= max bucket); a direct caller gets
        the loud error."""
        if n < 1:
            raise ValueError(f"n={n} must be >= 1")
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"n={n} exceeds the largest bucket {max(self.scfg.buckets)}"
        )

    @property
    def ragged_rows(self) -> int:
        """Static row capacity of every ragged signature (row slots past
        the gathered count mask out with n_patches 0)."""
        return self.scfg.max_batch

    @property
    def ragged_page_buckets(self) -> Tuple[int, ...]:
        """The ascending page-count ladder the ragged signatures
        precompile — `ServeConfig.ragged_pages` when set, else
        full-row-page strides from one full-resolution row up to
        max_batch rows (at most ~8 signatures). DENSER than buckets x
        pages-per-row on purpose: the ladder rounds a dispatch UP to its
        page count, and a coarse ladder hands the round-up right back to
        the pad tax the ragged route exists to kill."""
        if self.scfg.ragged_pages:
            return tuple(self.scfg.ragged_pages)
        from glom_tpu.serve.paged_columns import (
            pages_for_tokens,
            resolve_page_tokens,
        )

        ppr = pages_for_tokens(
            self.cfg.num_patches, resolve_page_tokens(self.cfg, self.scfg)
        )
        top = self.scfg.max_batch * ppr
        stride = ppr * max(1, -(-self.scfg.max_batch // 8))
        return tuple(range(stride, top + 1, stride))

    def pick_pages(self, n_pages: int) -> int:
        """Smallest ragged ladder entry admitting n_pages total pages
        (the page-axis pick_bucket)."""
        if n_pages < 1:
            raise ValueError(f"n_pages={n_pages} must be >= 1")
        for p in self.ragged_page_buckets:
            if n_pages <= p:
                return p
        raise ValueError(
            f"n_pages={n_pages} exceeds the largest ragged signature "
            f"{max(self.ragged_page_buckets)}"
        )

    def signature(
        self,
        bucket,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm=False,
    ) -> Tuple:
        if iters_override is not None:
            route = iters_override
        elif auto_budget is not None and self.iters_key == "auto":
            route = f"auto:{auto_budget}"
        else:
            route = self.iters_key
        return (bucket, route, self.scfg.use_pallas, warm)

    # -- compilation -------------------------------------------------------

    def _build_fn(
        self,
        bucket: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm: bool = False,
    ):
        """The pure forward for one signature: (params, img [bucket,c,H,W],
        mask [bucket][, levels0 [bucket,n,L,d]]) -> (levels
        [bucket,n,L,d], iters_run int32, row_converged [bucket] bool,
        row_iters [bucket] int32). The mask only matters on the auto route
        (pad rows must not vote on the early-exit witness or the quorum);
        the fixed route carries it for a uniform calling convention.

        iters_override (the degradation ladder's capped_iters rung) pins
        a FIXED budget regardless of the configured route; auto_budget
        caps the auto route's max_iters (a continuation dispatch runs its
        stragglers' REMAINING budget); warm compiles the variant taking a
        carried-in levels state. Each is its own memoized signature."""
        cfg, scfg = self.cfg, self.scfg
        compute_dtype = self._compute_dtype
        auto = iters_override is None and self.iters_key == "auto"
        if auto:
            max_iters = (
                auto_budget if auto_budget is not None else self.auto_budget
            )
        else:
            max_iters = (
                iters_override if iters_override is not None else self.iters_key
            )

        if self.mesh is not None:
            if warm == "paged-inc":
                raise ValueError(
                    "the incremental route rides the single-device paged "
                    "path only (sharded incremental is a documented "
                    "follow-on; docs/SERVING.md)"
                )
            from glom_tpu.parallel.serve_mesh import make_serve_forward

            return make_serve_forward(
                self.mesh, cfg,
                route="auto" if auto else max_iters,
                max_iters=max_iters if auto else None,
                threshold=scfg.exit_threshold,
                min_iters=min(scfg.min_iters, max_iters),
                quorum=scfg.exit_quorum,
                compute_dtype=compute_dtype,
                use_pallas=scfg.use_pallas,
                warm=warm is True,
                page_tokens=(
                    self.pool.page_tokens if warm == "paged" else None
                ),
                page_gather=getattr(scfg, "page_gather", "auto"),
            )

        if auto:

            def fn(params, img, mask, levels0=None):
                res = glom_forward_tiered(
                    params, img, cfg,
                    max_iters=max_iters,
                    threshold=scfg.exit_threshold,
                    min_iters=min(scfg.min_iters, max_iters),
                    quorum=scfg.exit_quorum,
                    levels=levels0,
                    valid_mask=mask,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                return res.levels, res.iters_run, res.row_converged, res.row_iters

        else:
            iters = max_iters

            def fn(params, img, mask, levels0=None):
                del mask  # pad rows are harmless on the fixed route
                final = glom_forward(
                    params, img, cfg, iters=iters,
                    levels=levels0,
                    compute_dtype=compute_dtype,
                    use_pallas=scfg.use_pallas,
                )
                b = final.shape[0]
                return (
                    final,
                    jnp.int32(iters),
                    jnp.ones((b,), bool),
                    jnp.full((b,), iters, jnp.int32),
                )

        if warm in ("paged", "paged-inc"):
            # The PAGED warm variant: levels0 never crosses the host
            # boundary — the dispatch carries tiny int32 page indices and
            # the compiled program assembles the warm state by a
            # page-index take from the device-resident pool
            # (serve/paged_columns.py). page_idx rows of -1 are COLD:
            # they take the forward's own init broadcast, bitwise the
            # cold_levels() contract. With a delta-chain page table the
            # indices are the session's EFFECTIVE base+Σdeltas map — the
            # reconstruction IS this same take.
            pt = self.pool.page_tokens

            def take_pages(params, pool, page_idx, b):
                with jax.named_scope("page_take"):
                    pages = pool[jnp.clip(page_idx, 0, pool.shape[0] - 1)]
                    init = jnp.broadcast_to(
                        params.init_levels[None],
                        (pt, cfg.levels, cfg.dim),
                    ).astype(pool.dtype)
                    pages = jnp.where(
                        (page_idx >= 0)[..., None, None, None], pages, init
                    )
                    return pages.reshape(
                        b, cfg.num_patches, cfg.levels, cfg.dim
                    )

            if warm == "paged-inc":
                # The INCREMENTAL route (docs/SERVING.md, "Delta
                # streaming"): the dispatch additionally carries the
                # input delta's [b, pages_per_row] page support — rows
                # whose frame did not change start pre-converged, changed
                # rows exit on the support-masked witness. auto-route
                # only (a fixed budget has no exit to seed).
                if not auto:
                    raise ValueError(
                        "the incremental route needs iters='auto' (a "
                        "fixed budget has no early exit to seed)"
                    )
                from glom_tpu.serve.early_exit import (
                    glom_forward_incremental,
                )

                def paged_inc_fn(params, img, mask, pool, page_idx, support):
                    b = img.shape[0]
                    levels0 = take_pages(params, pool, page_idx, b)
                    support_tok = jnp.repeat(support, pt, axis=1)  # [b, n]
                    res = glom_forward_incremental(
                        params, img, cfg,
                        max_iters=max_iters,
                        threshold=scfg.exit_threshold,
                        min_iters=min(scfg.min_iters, max_iters),
                        quorum=scfg.exit_quorum,
                        levels=levels0,
                        support_mask=support_tok,
                        valid_mask=mask,
                        compute_dtype=compute_dtype,
                        use_pallas=scfg.use_pallas,
                    )
                    return (
                        res.levels, res.iters_run,
                        res.row_converged, res.row_iters,
                    )

                return paged_inc_fn

            def paged_fn(params, img, mask, pool, page_idx):
                levels0 = take_pages(params, pool, page_idx, img.shape[0])
                return fn(params, img, mask, levels0)

            return paged_fn
        if warm:
            return fn
        return lambda params, img, mask: fn(params, img, mask)

    def _build_ragged_fn(
        self,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        cont: bool = False,
    ):
        """The ragged signature's pure forward
        (serve/early_exit.glom_forward_ragged): (params, patches
        [T, patch_dim], n_patches [R][, pool, page_idx [P]]) -> (levels
        [T, L, d], iters_run, row_converged [R], row_iters [R]). The
        pool args exist exactly when the engine owns a page pool — one
        program serves cold and page-warm ragged dispatches (cold pages
        are index -1). cont=True builds the CONTINUATION variant
        instead: (params, patches, n_patches, levels0 [T, L, d]) —
        straggler groups re-enter with host-carried warm state (ragged x
        continuation composition; page warmth does not apply, the rows'
        columns are mid-flight, not resolved)."""
        from glom_tpu.serve.early_exit import glom_forward_ragged

        cfg, scfg = self.cfg, self.scfg
        compute_dtype = self._compute_dtype
        auto = iters_override is None and self.iters_key == "auto"
        if auto:
            max_iters = (
                auto_budget if auto_budget is not None else self.auto_budget
            )
            route = "auto"
        else:
            route = max_iters = (
                iters_override if iters_override is not None else self.iters_key
            )
        pt = self.pool.page_tokens if self.pool is not None else None
        if pt is None:
            from glom_tpu.serve.paged_columns import resolve_page_tokens

            pt = resolve_page_tokens(cfg, scfg)
        kw = dict(
            page_tokens=pt,
            route=route,
            max_iters=max_iters if auto else None,
            threshold=scfg.exit_threshold,
            min_iters=min(scfg.min_iters, max_iters),
            quorum=scfg.exit_quorum,
            compute_dtype=compute_dtype,
            use_pallas=scfg.use_pallas,
            ragged_attention=scfg.ragged_attention,
        )
        if cont:

            def fn(params, patches, n_patches, levels0):
                res = glom_forward_ragged(
                    params, patches, cfg, n_patches=n_patches,
                    levels0=levels0, **kw,
                )
                return (
                    res.levels, res.iters_run,
                    res.row_converged, res.row_iters,
                )

        elif self.pool is not None:

            def fn(params, patches, n_patches, pool, page_idx):
                res = glom_forward_ragged(
                    params, patches, cfg, n_patches=n_patches,
                    pool=pool, page_idx=page_idx, **kw,
                )
                return (
                    res.levels, res.iters_run,
                    res.row_converged, res.row_iters,
                )

        else:

            def fn(params, patches, n_patches):
                res = glom_forward_ragged(
                    params, patches, cfg, n_patches=n_patches, **kw,
                )
                return (
                    res.levels, res.iters_run,
                    res.row_converged, res.row_iters,
                )

        return fn

    def _ragged_key(self, pages: int) -> str:
        """The ragged signature's bucket key. The attention mode rides
        the key when it departs from the default windowed gather — a
        banded program is a DIFFERENT compiled artifact (same bitwise
        outputs at threshold 0, per the parity suite), so it must not
        collide with a windowed signature compiled earlier in the same
        process."""
        mode = self.scfg.ragged_attention
        if mode == "windowed":
            return f"ragged{pages}"
        return f"ragged{pages}:{mode}"

    def _compile(
        self,
        bucket: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        warm: bool = False,
    ):
        """AOT-compile one bucket signature from abstract shapes and emit
        the "serve" warmup event (compile seconds attributed per bucket).
        Sharded signatures additionally run the lowering inside a
        collective-counting context, so the per-dispatch wire bytes land
        on the signature's stats record (while-loop sites price the
        BUDGET — see parallel/serve_mesh.py)."""
        sig = self.signature(
            bucket, iters_override, auto_budget=auto_budget, warm=warm
        )
        if sig in self._compiled:
            return self._compiled[sig]
        cfg = self.cfg
        img_abs = jax.ShapeDtypeStruct(
            (bucket, cfg.channels, cfg.image_size, cfg.image_size), jnp.float32
        )
        mask_abs = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        params_abs = jax.tree_util.tree_map(
            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), self.params
        )
        lv_dtype = (
            self._compute_dtype if self._compute_dtype is not None
            else jnp.float32
        )
        if warm in ("paged", "paged-inc"):
            pool = self.pool
            pool_abs = jax.ShapeDtypeStruct(
                (pool.n_pages, pool.page_tokens, cfg.levels, cfg.dim),
                pool.buffer().dtype,
            )
            pidx_abs = jax.ShapeDtypeStruct(
                (bucket, cfg.num_patches // pool.page_tokens), jnp.int32
            )
            abstract = (params_abs, img_abs, mask_abs, pool_abs, pidx_abs)
            if warm == "paged-inc":
                supp_abs = jax.ShapeDtypeStruct(
                    (bucket, cfg.num_patches // pool.page_tokens), jnp.bool_
                )
                abstract = abstract + (supp_abs,)
        else:
            lv_abs = jax.ShapeDtypeStruct(
                (bucket, cfg.num_patches, cfg.levels, cfg.dim), lv_dtype
            )
            abstract = (params_abs, img_abs, mask_abs) + (
                (lv_abs,) if warm else ()
            )
        # Donate the image batch, and the warm levels carry with it. The
        # POOL is never donated BY A DISPATCH: it is the persistent page
        # store every later dispatch reads. Write-backs update it on the
        # pool's own seam — copy-on-write by default, donated in place
        # under ServeConfig.pool_aliasing, gated by the read pins this
        # dispatch holds via acquire_read (serve/paged_columns.py).
        donate = (
            ((1, 3) if warm is True else (1,)) if self._donate else ()
        )
        fn = self._build_fn(
            bucket, iters_override, auto_budget=auto_budget, warm=warm
        )
        jit_kw = {"donate_argnums": donate}
        if self.mesh is not None:
            in_sh, out_sh = self._serve_shardings(warm)
            jit_kw.update(in_shardings=in_sh, out_shardings=out_sh)
        t0 = time.perf_counter()
        if self.mesh is not None:
            from glom_tpu.telemetry.counters import (
                CollectiveCounters,
                recording,
                timing,
            )

            counters = CollectiveCounters()
            # The timing context is TRACE-scoped: "full" makes every
            # registered site lower with its io_callback brackets (the
            # callbacks close over this engine's log); "sampled"/"off"
            # insert nothing. Either way the counting trace populates the
            # site registry the sampler re-dispatches from.
            with recording(counters), timing(
                self.collective_timing, self._coll_log
            ):
                lowered = jax.jit(fn, **jit_kw).lower(*abstract)
            self._comm[sig] = counters.totals()
            # A lazy mid-traffic compile runs on a WORKER thread while
            # another worker's sampling tick reads the registry: the
            # merge rides the same lock.
            with self._coll_lock:
                for site in counters.sites:
                    self._coll_sites.setdefault(
                        (site["site"], site["shape"]), site
                    )
        else:
            lowered = jax.jit(fn, **jit_kw).lower(*abstract)
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        self._compiled[sig] = compiled
        self._stats.setdefault(sig, StepTimeStats()).observe(dt, is_compile=True)
        self._emit(
            {
                "event": "warmup",
                "bucket": bucket,
                "iters": sig[1],
                "warm_state": warm,
                "degraded": iters_override is not None,
                "sharded": self.mesh is not None,
                "use_pallas": self.scfg.use_pallas,
                "compile_time_s": round(dt, 4),
            }
        )
        return compiled

    def _compile_ragged(
        self,
        pages: int,
        iters_override: Optional[int] = None,
        *,
        auto_budget: Optional[int] = None,
        cont: bool = False,
    ):
        """AOT-compile one RAGGED page-count signature (flat token axis
        of pages x page_tokens; the pool args exactly when the engine
        owns one). Same warmup-event discipline as the bucket route.
        cont=True compiles the continuation variant (warm levels0 rides
        the dispatch; the straggler re-entry path)."""
        if cont:
            warm = "cont"
        else:
            warm = "pool" if self.pool is not None else "ragged"
        sig = self.signature(
            self._ragged_key(pages), iters_override,
            auto_budget=auto_budget, warm=warm,
        )
        if sig in self._compiled:
            return self._compiled[sig]
        from glom_tpu.serve.paged_columns import resolve_page_tokens

        cfg = self.cfg
        pt = (
            self.pool.page_tokens if self.pool is not None
            else resolve_page_tokens(cfg, self.scfg)
        )
        T = pages * pt
        patches_abs = jax.ShapeDtypeStruct((T, cfg.patch_dim), jnp.float32)
        n_abs = jax.ShapeDtypeStruct((self.ragged_rows,), jnp.int32)
        params_abs = jax.tree_util.tree_map(
            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), self.params
        )
        abstract = (params_abs, patches_abs, n_abs)
        if cont:
            lv_dtype = (
                self._compute_dtype if self._compute_dtype is not None
                else jnp.float32
            )
            lv_abs = jax.ShapeDtypeStruct(
                (T, cfg.levels, cfg.dim), lv_dtype
            )
            abstract = abstract + (lv_abs,)
            # Patches AND the carried levels donate — the straggler's
            # warm state is consumed by exactly this dispatch.
            donate = (1, 3) if self._donate else ()
        else:
            if self.pool is not None:
                pool_abs = jax.ShapeDtypeStruct(
                    (self.pool.n_pages, pt, cfg.levels, cfg.dim),
                    self.pool.buffer().dtype,
                )
                pidx_abs = jax.ShapeDtypeStruct((pages,), jnp.int32)
                abstract = abstract + (pool_abs, pidx_abs)
            donate = (1,) if self._donate else ()
        fn = self._build_ragged_fn(
            iters_override, auto_budget=auto_budget, cont=cont
        )
        t0 = time.perf_counter()
        compiled = jax.jit(fn, donate_argnums=donate).lower(
            *abstract
        ).compile()
        dt = time.perf_counter() - t0
        self._compiled[sig] = compiled
        self._stats.setdefault(sig, StepTimeStats()).observe(
            dt, is_compile=True
        )
        self._emit(
            {
                "event": "warmup",
                "bucket": sig[0],
                "iters": sig[1],
                "warm_state": sig[3],
                "degraded": iters_override is not None,
                "sharded": False,
                "use_pallas": self.scfg.use_pallas,
                "compile_time_s": round(dt, 4),
            }
        )
        return compiled

    def warmup(
        self,
        buckets: Optional[Tuple[int, ...]] = None,
        *,
        iters_override: Optional[int] = None,
        warm: bool = False,
    ) -> dict:
        """Precompile every bucket signature BEFORE traffic. Returns
        {bucket: compile_seconds}; already-compiled signatures are free.
        Call again with iters_override=<degraded budget> to pre-warm the
        ladder's capped_iters route, or warm=True for the continuation
        path's warm-state shape (continuation dispatches at partial
        budgets still compile on first miss — each remaining budget is
        its own signature, attributable in the warmup stream)."""
        out = {}
        for b in buckets if buckets is not None else self.scfg.buckets:
            sig = self.signature(b, iters_override, warm=warm)
            already = sig in self._compiled
            t0 = time.perf_counter()
            self._compile(b, iters_override, warm=warm)
            out[b] = 0.0 if already else time.perf_counter() - t0
        return out

    def warmup_ragged(
        self, pages: Optional[Tuple[int, ...]] = None
    ) -> dict:
        """Precompile the RAGGED page-count ladder (and, with a pool,
        the bucket route's PAGED warm signatures ride warmup(warm=...)
        as usual). Returns {page_count: compile_seconds}."""
        out = {}
        for p in pages if pages is not None else self.ragged_page_buckets:
            sig = self.signature(
                self._ragged_key(p),
                warm="pool" if self.pool is not None else "ragged",
            )
            already = sig in self._compiled
            t0 = time.perf_counter()
            self._compile_ragged(p)
            out[p] = 0.0 if already else time.perf_counter() - t0
        return out

    # -- dispatch ----------------------------------------------------------

    def _serve_shardings(self, warm) -> Tuple:
        """Memoized (in_shardings, out_shardings) for the sharded route —
        resolved once per (engine, warm mode) rather than per dispatch
        (the param tree_map is pure overhead in the request hot path).
        warm is False | True (host levels0 carry) | "paged" (pool +
        page-index take)."""
        if warm not in self._shardings:
            from glom_tpu.parallel.serve_mesh import serve_shardings

            self._shardings[warm] = serve_shardings(
                self.mesh, self.params,
                warm=warm is True, paged=warm == "paged",
            )
        return self._shardings[warm]

    def _device_input(self, src, sharding_spec=None):
        """One fresh device buffer per attempt (donation invalidates the
        previous one). On the sharded route the host array device_puts
        straight into its NamedSharding; single-device keeps the plain
        transfer."""
        if self.mesh is not None and sharding_spec is not None:
            return jax.device_put(np.asarray(src), sharding_spec)
        return jnp.asarray(src)

    def infer(
        self,
        imgs,
        n_valid: Optional[int] = None,
        *,
        iters_override: Optional[int] = None,
        levels0=None,
        auto_budget: Optional[int] = None,
        page_rows=None,
        support_rows=None,
    ) -> ServeResult:
        """Run one padded batch. `imgs` is [b, c, H, W] (numpy or jax) with
        b equal to a bucket size — callers that batch themselves pass an
        exact bucket; the DynamicBatcher always does. `n_valid` marks how
        many leading rows are real requests (default: all).

        iters_override pins a fixed iteration budget for THIS dispatch
        (the degradation ladder's capped_iters rung); None runs the
        configured route. levels0 [b, n, L, d] carries warm column state
        in (the continuation path), and auto_budget caps the auto route's
        max_iters to the stragglers' remaining budget. page_rows
        [b, pages_per_row] int32 selects the PAGED warm signature
        instead: each row's levels0 assembles in-graph from the engine's
        pool pages (-1 rows take the cold init) — zero levels0 bytes
        cross the host boundary (serve/paged_columns.py). Transient dispatch
        failures retry per the engine's RetryPolicy — a failed attempt
        against an up-or-flapping backend backs off and re-dispatches from
        FRESH input buffers (donation invalidates the old ones), while a
        down backend raises straight into the batcher's shed path."""
        if iters_override is not None and (
            not isinstance(iters_override, int) or iters_override < 1
        ):
            raise ValueError(
                f"iters_override={iters_override!r}: an int >= 1 or None"
            )
        if auto_budget is not None:
            if not isinstance(auto_budget, int) or auto_budget < 1:
                raise ValueError(
                    f"auto_budget={auto_budget!r}: an int >= 1 or None"
                )
            if iters_override is not None:
                raise ValueError(
                    "auto_budget composes with the auto route only, not "
                    "with a fixed iters_override"
                )
        b = np.shape(imgs)[0]
        if b not in self.scfg.buckets:
            raise ValueError(
                f"batch {b} is not a bucket shape {self.scfg.buckets}; pad "
                "to a bucket (DynamicBatcher does) or add the bucket"
            )
        n_valid = b if n_valid is None else n_valid
        if not 1 <= n_valid <= b:
            raise ValueError(f"n_valid={n_valid} outside 1..{b}")
        if page_rows is not None:
            if self.pool is None:
                raise ValueError(
                    "page_rows needs a page pool "
                    "(ServeConfig.page_pool_pages > 0)"
                )
            if levels0 is not None:
                raise ValueError("pass levels0 OR page_rows, not both")
            page_rows = np.asarray(page_rows, np.int32)
            ppr = self.cfg.num_patches // self.pool.page_tokens
            if page_rows.shape != (b, ppr):
                raise ValueError(
                    f"page_rows shape {page_rows.shape} != ({b}, {ppr})"
                )
        if support_rows is not None:
            # The INCREMENTAL route: a paged dispatch carrying the input
            # delta's page support (docs/SERVING.md, "Delta streaming").
            if page_rows is None:
                raise ValueError(
                    "support_rows rides page_rows (the incremental route "
                    "is a paged dispatch)"
                )
            if self.iters_key != "auto" or iters_override is not None:
                raise ValueError(
                    "support_rows needs the iters='auto' route (a fixed "
                    "budget has no early exit to seed)"
                )
            if self.mesh is not None:
                raise ValueError(
                    "the incremental route rides the single-device paged "
                    "path only (sharded incremental is a follow-on)"
                )
            support_rows = np.asarray(support_rows, bool)
            if support_rows.shape != page_rows.shape:
                raise ValueError(
                    f"support_rows shape {support_rows.shape} != "
                    f"{page_rows.shape}"
                )
        if page_rows is not None:
            warm = "paged-inc" if support_rows is not None else "paged"
        else:
            warm = levels0 is not None
        if warm is True and np.shape(levels0)[0] != b:
            raise ValueError(
                f"levels0 batch {np.shape(levels0)[0]} != bucket {b}"
            )
        lv_dtype = (
            self._compute_dtype if self._compute_dtype is not None
            else np.float32
        )
        img_sh = mask_sh = lv_sh = pidx_sh = None
        if self.mesh is not None:
            in_sh, _ = self._serve_shardings(warm)
            img_sh, mask_sh = in_sh[1], in_sh[2]
            lv_sh = in_sh[3] if warm is True else None
            pidx_sh = in_sh[4] if warm == "paged" else None
        levels0_h2d = [0]
        if self._donate:
            # Every ATTEMPT needs fresh device buffers: the compiled call
            # donates its inputs, so a retry after a failed dispatch must
            # never reuse a possibly-invalidated array. Hold the sources
            # on the HOST (np.asarray of a caller-held jax array fetches a
            # copy, so the caller's buffer is never the donated one) and
            # re-transfer per attempt.
            src = np.asarray(imgs, np.float32)
            make_input = lambda: self._device_input(src, img_sh)
            if warm is True:
                lv_src = np.asarray(levels0, lv_dtype)

                def make_levels():
                    levels0_h2d[0] += lv_src.nbytes
                    return self._device_input(lv_src, lv_sh)

            else:
                make_levels = None
        else:
            dev = self._device_input(np.asarray(imgs, np.float32), img_sh)
            make_input = lambda: dev
            if warm is True:
                lv_src = np.asarray(levels0, lv_dtype)
                levels0_h2d[0] += lv_src.nbytes
                lv_dev = self._device_input(lv_src, lv_sh)
                make_levels = lambda: lv_dev
            else:
                make_levels = None
        mask_host = np.arange(b) < n_valid
        mask = (
            jax.device_put(mask_host, mask_sh)
            if mask_sh is not None
            else jnp.asarray(mask_host)
        )
        if warm in ("paged", "paged-inc"):
            # The whole point: the warm state stays device-resident —
            # only the tiny int32 page map (plus, on the incremental
            # route, the bool support map) crosses the host boundary.
            pidx_dev = (
                jax.device_put(page_rows, pidx_sh)
                if pidx_sh is not None
                else jnp.asarray(page_rows)
            )
            supp_dev = (
                jnp.asarray(support_rows) if warm == "paged-inc" else None
            )
        sig = self.signature(
            b, iters_override, auto_budget=auto_budget, warm=warm
        )
        compiled_before = sig in self._compiled
        fn = self._compile(
            b, iters_override, auto_budget=auto_budget, warm=warm
        )
        stats = self._stats.setdefault(sig, StepTimeStats())
        attempts = [0]
        # Latency decomposition (ServeConfig.phase_split, default ON): the
        # engine attributes its own wall between h2d (staging the inputs,
        # forced resident with block_until_ready — without the sync the
        # async transfer would hide inside the compiled call) and resolve
        # (fetching the outputs back); the compiled call plus whatever the
        # split cannot see (validation, retry backoff) is the batcher's
        # device_ms remainder. Accumulated across retry attempts, like
        # levels0_h2d.
        split = self.phase_split
        ph = {"h2d_s": 0.0, "resolve_s": 0.0}

        def attempt():
            attempts[0] += 1
            if self._fault_hook is not None:
                self._fault_hook(
                    {"bucket": b, "n_valid": n_valid, "attempt": attempts[0]}
                )
            t_h = time.perf_counter()
            staged = make_input()
            args = (self.params, staged, mask)
            lv_staged = None
            pinned = False
            try:
                if warm in ("paged", "paged-inc"):
                    # Snapshot per attempt: the freshest write-backs,
                    # PINNED for the dispatch's lifetime — under pool
                    # aliasing the pin blocks donation of the buffer
                    # this program reads (a CoW pool is unaffected; the
                    # pin is a free counter).
                    args = args + (self.pool.acquire_read(), pidx_dev)
                    pinned = True
                    if warm == "paged-inc":
                        args = args + (supp_dev,)
                elif warm:
                    lv_staged = make_levels()
                    args = args + (lv_staged,)
                if split:
                    jax.block_until_ready(staged)
                    if lv_staged is not None:
                        jax.block_until_ready(lv_staged)
                    ph["h2d_s"] += time.perf_counter() - t_h
                # args is attempt-local and never read after the dispatch:
                # every attempt rebuilds it from make_input()/make_levels(),
                # so a donated buffer is re-staged before any retry reads it.
                # glom-lint: ok[donation-safety] attempt-local splat, rebuilt per retry
                levels, iters_run, conv, row_iters = fn(*args)
                levels.block_until_ready()  # syncs: serving is request/
                # response — the caller needs the answer now, and the
                # wait IS the device latency being measured.
            finally:
                if pinned:
                    self.pool.release_read()
            t_r = time.perf_counter()
            iters_host = int(jax.device_get(iters_run))
            out = (
                levels,
                iters_host,
                np.asarray(jax.device_get(conv)),
                np.asarray(jax.device_get(row_iters)),
            )
            if split:
                ph["resolve_s"] += time.perf_counter() - t_r
            return out

        t0 = time.perf_counter()
        if self.retry is not None:
            out = self.retry.run(attempt, bucket=b, n_valid=n_valid)
        else:
            out = attempt()
        levels, iters_host, conv, row_iters = out
        dt = time.perf_counter() - t0
        stats.observe(dt, is_compile=False)
        self.levels0_h2d_bytes_total += levels0_h2d[0]
        self._tick_collective_timing()
        return ServeResult(
            levels=levels,
            iters_run=iters_host,
            latency_s=dt,
            bucket=b,
            compiled=not compiled_before,
            row_converged=conv,
            row_iters=row_iters,
            levels0_h2d_bytes=levels0_h2d[0],
            phases=(
                {"h2d_ms": 1e3 * ph["h2d_s"],
                 "resolve_ms": 1e3 * ph["resolve_s"]}
                if split else None
            ),
        )

    def infer_ragged(
        self,
        patches,
        n_patches,
        *,
        page_idx=None,
        levels0=None,
        auto_budget: Optional[int] = None,
        iters_override: Optional[int] = None,
    ) -> RaggedServeResult:
        """Run one RAGGED dispatch: rows of DIFFERING patch counts packed
        page-aligned on a flat token axis (docs/SERVING.md, "Ragged
        admission").

        patches: [T, patch_dim] host-patchified rows in row order, page
        padded (T = P x page_tokens with P a ragged-ladder entry — the
        batcher packs with the same `ragged_row_layout` the compiled
        program derives in-graph). n_patches: per-row patch counts (at
        most `ragged_rows`; padded with 0 internally). page_idx: [P]
        int32 pool pages per dispatch-page slot, -1 = cold (requires the
        engine's pool; None = all cold). Warm state rides the POOL ONLY
        — there is no host levels0 on this route, which is exactly what
        `levels0_h2d_bytes == 0` asserts. EXCEPT the continuation
        re-entry: levels0 [T, L, d] flat (row-packed like patches)
        carries straggler groups' mid-flight columns back in (mutually
        exclusive with page_idx — unresolved state has no pages), and
        its H2D bytes are reported, not asserted zero."""
        if self.mesh is not None:
            raise ValueError("ragged dispatch: single-device route only")
        if iters_override is not None and (
            not isinstance(iters_override, int) or iters_override < 1
        ):
            raise ValueError(
                f"iters_override={iters_override!r}: an int >= 1 or None"
            )
        if auto_budget is not None:
            if not isinstance(auto_budget, int) or auto_budget < 1:
                raise ValueError(
                    f"auto_budget={auto_budget!r}: an int >= 1 or None"
                )
            if iters_override is not None:
                raise ValueError(
                    "auto_budget composes with the auto route only"
                )
        from glom_tpu.serve.paged_columns import (
            pages_for_tokens,
            resolve_page_tokens,
        )

        pt = (
            self.pool.page_tokens if self.pool is not None
            else resolve_page_tokens(self.cfg, self.scfg)
        )
        patches = np.asarray(patches, np.float32)
        T = patches.shape[0]
        if T % pt != 0:
            raise ValueError(f"T={T} is not a multiple of page_tokens {pt}")
        P = T // pt
        if P not in self.ragged_page_buckets:
            raise ValueError(
                f"{P} pages is not a ragged signature "
                f"{self.ragged_page_buckets}; pack to a ladder entry "
                "(DynamicBatcher does)"
            )
        n_list = [int(n) for n in np.asarray(n_patches).reshape(-1)]
        R = self.ragged_rows
        if len(n_list) > R:
            raise ValueError(f"{len(n_list)} rows exceed ragged_rows {R}")
        if any(n < 0 or n > self.cfg.num_patches for n in n_list):
            raise ValueError(
                f"n_patches {n_list}: each row needs 0..{self.cfg.num_patches}"
                " patches (the pos table bounds the row length)"
            )
        need = sum(pages_for_tokens(n, pt) for n in n_list if n > 0)
        if need > P:
            raise ValueError(f"rows need {need} pages > dispatch size {P}")
        n_host = np.zeros((R,), np.int32)
        n_host[: len(n_list)] = n_list
        if page_idx is not None and self.pool is None:
            raise ValueError(
                "page_idx needs a page pool (ServeConfig.page_pool_pages)"
            )
        cont = levels0 is not None
        if cont:
            if page_idx is not None:
                raise ValueError(
                    "levels0 OR page_idx: a continuation's columns are "
                    "mid-flight, not pool-resident"
                )
            lv_dtype = (
                self._compute_dtype if self._compute_dtype is not None
                else jnp.float32
            )
            lv_host = np.asarray(levels0)
            if lv_host.shape != (T, self.cfg.levels, self.cfg.dim):
                raise ValueError(
                    f"levels0 shape {lv_host.shape} != "
                    f"({T}, {self.cfg.levels}, {self.cfg.dim}) (flat "
                    "row-packed, page padded like patches)"
                )
        if self.pool is not None and not cont:
            pidx_host = (
                np.full((P,), -1, np.int32) if page_idx is None
                else np.asarray(page_idx, np.int32)
            )
            if pidx_host.shape != (P,):
                raise ValueError(
                    f"page_idx shape {pidx_host.shape} != ({P},)"
                )
        if cont:
            warm = "cont"
        else:
            warm = "pool" if self.pool is not None else "ragged"
        sig = self.signature(
            self._ragged_key(P), iters_override,
            auto_budget=auto_budget, warm=warm,
        )
        compiled_before = sig in self._compiled
        fn = self._compile_ragged(
            P, iters_override, auto_budget=auto_budget, cont=cont
        )
        stats = self._stats.setdefault(sig, StepTimeStats())
        n_dev = jnp.asarray(n_host)
        attempts = [0]
        split = self.phase_split
        ph = {"h2d_s": 0.0, "resolve_s": 0.0}
        levels0_h2d = [0]

        def attempt():
            attempts[0] += 1
            if self._fault_hook is not None:
                self._fault_hook(
                    {
                        "bucket": self._ragged_key(P),
                        "n_valid": sum(1 for n in n_list if n > 0),
                        "attempt": attempts[0],
                    }
                )
            t_h = time.perf_counter()
            staged = jnp.asarray(patches)
            args = (self.params, staged, n_dev)
            pinned = False
            try:
                if cont:
                    lv_staged = jnp.asarray(lv_host.astype(lv_dtype))
                    levels0_h2d[0] += lv_staged.nbytes
                    args = args + (lv_staged,)
                elif self.pool is not None:
                    # Pin the snapshot for the dispatch's whole
                    # lifetime: under pool aliasing the pin blocks
                    # donation of the buffer this program reads (a CoW
                    # pool is unaffected — the pin is a free counter).
                    args = args + (
                        self.pool.acquire_read(), jnp.asarray(pidx_host)
                    )
                    pinned = True
                if split:
                    jax.block_until_ready(staged)
                    ph["h2d_s"] += time.perf_counter() - t_h
                # args is attempt-local and never read after the dispatch:
                # every attempt rebuilds it from make_input()/make_levels(),
                # so a donated buffer is re-staged before any retry reads it.
                # glom-lint: ok[donation-safety] attempt-local splat, rebuilt per retry
                levels, iters_run, conv, row_iters = fn(*args)
                levels.block_until_ready()
            finally:
                if pinned:
                    self.pool.release_read()
            t_r = time.perf_counter()
            out = (
                levels,
                int(jax.device_get(iters_run)),
                np.asarray(jax.device_get(conv)),
                np.asarray(jax.device_get(row_iters)),
            )
            if split:
                ph["resolve_s"] += time.perf_counter() - t_r
            return out

        t0 = time.perf_counter()
        if self.retry is not None:
            out = self.retry.run(
                attempt, bucket=self._ragged_key(P),
                n_valid=sum(1 for n in n_list if n > 0),
            )
        else:
            out = attempt()
        levels, iters_host, conv, row_iters = out
        dt = time.perf_counter() - t0
        stats.observe(dt, is_compile=False)
        self.levels0_h2d_bytes_total += levels0_h2d[0]
        return RaggedServeResult(
            levels=levels,
            iters_run=iters_host,
            latency_s=dt,
            pages=P,
            compiled=not compiled_before,
            row_converged=conv,
            row_iters=row_iters,
            levels0_h2d_bytes=levels0_h2d[0],
            phases=(
                {"h2d_ms": 1e3 * ph["h2d_s"],
                 "resolve_ms": 1e3 * ph["resolve_s"]}
                if split else None
            ),
        )

    # -- telemetry ---------------------------------------------------------

    def _tick_collective_timing(self) -> None:
        """Sampled-mode cadence: every collective_timing_interval-th
        dispatch re-dispatches each registered site as its own timed
        sub-graph (telemetry/comm_time.py) and buffers the stamped
        records for collective_time_records(). The sample runs ON the
        dispatching thread after its result is already resolved — the
        cost lands on one dispatch in N, which is exactly what the
        collective-timing overhead A/B prices."""
        if self.collective_timing != "sampled":
            return
        # The cheap lock decides DUE and snapshots the registry; the
        # sampling pass itself (sub-graph compiles + timed dispatches —
        # seconds on a first tick) runs under the dedicated sample lock
        # so a concurrent dispatch's tick only ever waits for the
        # counter, never for another thread's sample.
        with self._coll_lock:
            if not self._coll_sites:
                return
            self._coll_dispatches += 1
            if (
                self._coll_dispatches
                % self.scfg.collective_timing_interval != 0
            ):
                return
            sites = list(self._coll_sites.values())
        from glom_tpu.telemetry.comm_time import (
            CollectiveTimeSampler,
            collective_time_records,
        )

        with self._coll_sample_lock:
            if self._coll_sampler is None:
                self._coll_sampler = CollectiveTimeSampler(
                    self.mesh, sites, interval=1
                )
            else:
                # Sites registered by lazy compiles AFTER the sampler was
                # built (a new bucket/warm signature) join the rotation —
                # a frozen registry would silently never time them.
                self._coll_sampler.update_sites(sites)
            recs = collective_time_records(
                self._coll_sampler.sample(), path=self.name,
                mode="sampled",
            )
        with self._coll_lock:
            self._coll_samples.extend(
                dict(r, engine=self.name) for r in recs
            )

    def collective_time_records(self) -> list:
        """Drain the per-collective wall-time evidence: full-mode
        io_callback brackets aggregate per (site, axis, bytes); sampled-
        mode buffered re-dispatch rows pass through. Every row is a
        stamped schema "collective_time" record carrying the α-β
        comm_time_model fit + drift; empty when timing is off (the
        acceptance contract: off-mode leaves NO records)."""
        out: list = []
        if self._coll_log is not None:
            samples = self._coll_log.drain()
            if samples:
                from glom_tpu.telemetry.comm_time import (
                    collective_time_records,
                )

                out.extend(
                    dict(r, engine=self.name)
                    for r in collective_time_records(
                        samples, path=self.name, mode="full"
                    )
                )
        with self._coll_lock:
            buffered, self._coll_samples = self._coll_samples, []
        out.extend(buffered)
        return out

    def release(self) -> None:
        """Free this engine's device-side state after a graceful drain
        (serve/elastic.py scale-in, step 4: release devices). Drops the
        memoized compiled executables, the sharding/cold-init caches,
        and the page pool's buffer + table — the HBM a drained replica
        was holding. The object stays a valid EVIDENCE husk (name,
        stats_records, collective_time_records) but can no longer serve;
        the batcher has already removed it from the fleet, so nothing
        dispatches here again."""
        self._compiled.clear()
        self._shardings.clear()
        self._cold_levels = None
        self.released = True
        if self.pool is not None:
            self.pool.release()
        self._emit({"event": "engine_release"})

    def _emit(self, rec: dict) -> None:
        from glom_tpu.serve.events import emit_serve

        emit_serve(self.writer, dict(rec, engine=self.name))

    def stats_records(self) -> list:
        """One stamped "serve" event per compiled signature with the
        per-bucket latency histogram (p50/p95/p99/max, compile split) and,
        on the sharded route, the counted per-dispatch collective wire
        bytes from the lowering trace."""
        out = []
        for sig, stats in sorted(
            self._stats.items(), key=lambda kv: str(kv[0])
        ):
            bucket, iters_key, pallas, warm = sig
            rec = {
                "event": "bucket_stats",
                "engine": self.name,
                "bucket": bucket,
                "iters": iters_key,
                "warm_state": warm,
                "use_pallas": pallas,
                **stats.summary(),
            }
            if sig in self._comm:
                rec.update(self._comm[sig])
            out.append(schema.stamp(rec, kind="serve"))
        return out
