"""The one serve-record emitter: stamp kind, merge backend state, route.

Every serving sink (engine warmups/bucket stats, batcher dispatches/sheds,
CLI responses) speaks the same record discipline as sinks.emit's bench
rows — schema-stamped, current watchdog backend state merged (keys already
present win), delivered to the writer when one is attached else to the
global flight recorder. One definition; the callers must not re-copy the
stamp+merge sequence."""

from __future__ import annotations

from glom_tpu.telemetry import schema


def stamp_serve(rec: dict, kind: str = "serve") -> dict:
    """Stamped copy of `rec` carrying kind + the watchdog backend state +
    (when this thread is inside a batcher dispatch scope) the dispatch's
    trace context — so retry events, cache evictions, and lazy mid-traffic
    warmup compiles emitted from under a dispatch join that request's
    trace tree without any signature threading (telemetry/tracectx.py).
    Keys already present always win."""
    from glom_tpu.telemetry import tracectx
    from glom_tpu.telemetry.watchdog import backend_record

    stamped = schema.stamp(rec, kind=kind)
    for k, v in backend_record().items():
        stamped.setdefault(k, v)
    if not any(k in stamped for k in ("trace_id", "trace_ids")):
        # Records that carry their OWN trace identity (a per-request
        # resolve leaf, say) are never widened to the whole batch scope.
        stamped.update(tracectx.current_fields())
    return stamped


def emit_serve(writer, rec: dict, kind: str = "serve") -> dict:
    """stamp_serve + writer-else-flight delivery; returns the stamped
    record (the CLI reuses it for response accounting)."""
    from glom_tpu.tracing.flight import write_or_observe

    stamped = stamp_serve(rec, kind=kind)
    write_or_observe(writer, stamped)
    return stamped
