"""`python -m glom_tpu.serve` — the stdin/file micro-server.

Not a network server (that is a frontend's job); this is the operational
harness for DRIVING the serving stack — warmup, admission, early exit,
telemetry — from a shell or a CI job, the same way train/cli.py drives the
trainer. Requests come from `--synthetic N` (seeded gaussian images — the
reproducible load generator) or `--requests FILE|-` (JSON lines
`{"id": ..., "seed": ...}`; images are generated from the seed, so request
files stay bytes not tensors). Every response, dispatch, warmup, and shed
lands as a schema-v3 record in the metrics stream — the output of a serve
run lints with `python -m glom_tpu.telemetry FILE` like any other artifact
of record, and CI runs exactly that smoke.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Iterable, Tuple


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m glom_tpu.serve",
        description="GLOM batched-inference micro-server (docs/SERVING.md)",
    )
    p.add_argument("--preset", default="mnist", help="see glom_tpu.utils.presets")
    p.add_argument(
        "--synthetic", type=int, default=None, metavar="N",
        help="serve N seeded synthetic requests (the reproducible load)",
    )
    p.add_argument(
        "--requests", default=None, metavar="FILE",
        help="JSONL request source ('-' = stdin): {\"id\":..., \"seed\":...}",
    )
    p.add_argument(
        "--iters", default=None,
        help="forward iteration budget: an int, or 'auto' for consensus "
        "early exit (serve/early_exit)",
    )
    p.add_argument(
        "--exit-threshold", type=float, default=None, metavar="D",
        help="iters=auto: exit once no level's agreement moves more than D "
        "between iterations (0 disables the exit — full budget always runs)",
    )
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--max-delay-ms", type=float, default=None)
    p.add_argument("--queue-depth", type=int, default=None)
    p.add_argument(
        "--buckets", default=None, metavar="B1,B2,...",
        help="ascending batch buckets to precompile (default: preset's)",
    )
    p.add_argument(
        "--no-warmup", action="store_true",
        help="skip the AOT warmup (buckets then compile on first miss — "
        "the latency cliff warmup exists to remove; for A/B only)",
    )
    p.add_argument(
        "--ladder", action="store_true",
        help="enable the degradation ladder (glom_tpu/resilience/ladder): "
        "under queue pressure or a flapping backend, step down capped-iters "
        "-> capped-buckets -> shed instead of shedding outright "
        "(docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--dispatch-retries", type=int, default=None, metavar="N",
        help="transient dispatch failures retry up to N times with backoff "
        "(watchdog-aware: a DOWN backend never retries; default: preset's)",
    )
    p.add_argument("--out", default=None, help="JSONL metrics path")
    p.add_argument(
        "--flight-recorder", default=None, metavar="DIR",
        help="crash flight recorder over the serve event stream",
    )
    return p


def _req_source(args) -> Iterable[Tuple[object, int]]:
    """(request id, seed) pairs from --synthetic or --requests."""
    if args.synthetic is not None:
        for i in range(args.synthetic):
            yield i, i
        return
    fh = sys.stdin if args.requests == "-" else open(args.requests)
    try:
        for line in fh:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            rec = json.loads(line)
            yield rec.get("id"), int(rec.get("seed", 0))
    finally:
        if fh is not sys.stdin:
            fh.close()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if (args.synthetic is None) == (args.requests is None):
        print(
            "exactly one of --synthetic N or --requests FILE required",
            file=sys.stderr,
        )
        return 2

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.serve.events import stamp_serve as serve_rec
    from glom_tpu.utils.metrics import MetricsWriter
    from glom_tpu.utils.presets import get_preset

    preset = get_preset(args.preset)
    cfg = preset.model
    scfg = preset.serve
    overrides = {}
    if args.iters is not None:
        overrides["iters"] = (
            "auto" if args.iters == "auto" else int(args.iters)
        )
    if args.exit_threshold is not None:
        overrides["exit_threshold"] = args.exit_threshold
    if args.max_batch is not None:
        overrides["max_batch"] = args.max_batch
    if args.max_delay_ms is not None:
        overrides["max_delay_ms"] = args.max_delay_ms
    if args.queue_depth is not None:
        overrides["queue_depth"] = args.queue_depth
    if args.buckets is not None:
        overrides["buckets"] = tuple(
            int(b) for b in args.buckets.split(",") if b
        )
    if args.ladder:
        overrides["ladder"] = True
    if args.dispatch_retries is not None:
        overrides["dispatch_retries"] = args.dispatch_retries
    if overrides:
        scfg = dataclasses.replace(scfg, **overrides)

    writer = MetricsWriter(args.out, echo=True)
    fr = None
    if args.flight_recorder:
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )

        fr = FlightRecorder(args.flight_recorder)
        fr.install_process_hooks()
        set_global_flight_recorder(fr)

    try:
        engine = InferenceEngine(cfg, scfg, writer=writer)
        ladder = None
        if scfg.ladder:
            from glom_tpu.resilience.ladder import DegradationLadder

            ladder = DegradationLadder.from_config(cfg, scfg, writer=writer)
        if not args.no_warmup:
            engine.warmup()
            if ladder is not None:
                # Pre-warm the capped-iters route too: the first degraded
                # dispatch must not pay a mid-traffic compile on top of
                # the pressure that degraded it.
                engine.warmup(iters_override=ladder.degraded_iters)

        rng_img = lambda seed: np.random.default_rng(seed).normal(
            size=(cfg.channels, cfg.image_size, cfg.image_size)
        ).astype(np.float32)

        served = failed = 0
        with DynamicBatcher(engine, writer=writer, ladder=ladder) as batcher:
            tickets = []
            for rid, seed in _req_source(args):
                try:
                    tickets.append((rid, batcher.submit(rng_img(seed))))
                except ShedError as e:
                    failed += 1
                    writer.write(
                        serve_rec(
                            {
                                "event": "response",
                                "id": rid,
                                "ok": False,
                                "reason": f"{type(e).__name__}: {e}"[:200],
                            }
                        )
                    )
            for rid, ticket in tickets:
                try:
                    levels, iters_run, latency_s = ticket.result(timeout=300.0)
                except Exception as e:  # noqa: BLE001 — per-request record
                    failed += 1
                    writer.write(
                        serve_rec(
                            {
                                "event": "response",
                                "id": rid,
                                "ok": False,
                                "reason": f"{type(e).__name__}: {e}"[:200],
                            }
                        )
                    )
                    continue
                served += 1
                writer.write(
                    serve_rec(
                        {
                            "event": "response",
                            "id": rid,
                            "ok": True,
                            "latency_ms": round(1e3 * latency_s, 3),
                            "iters_run": iters_run,
                            "top_level_norm": round(
                                float(np.linalg.norm(levels[:, -1]) / levels.shape[0]),
                                4,
                            ),
                        }
                    )
                )
            writer.write(serve_rec(batcher.summary_record()))
            for rec in batcher.span_records():
                writer.write(rec)
        for rec in engine.stats_records():
            writer.write(serve_rec(rec))
        return 0 if failed == 0 and served > 0 else 1
    finally:
        writer.close()
        if fr is not None:
            fr.dump("run-end")
            from glom_tpu.tracing.flight import set_global_flight_recorder

            set_global_flight_recorder(None)


if __name__ == "__main__":
    sys.exit(main())
