"""`python -m glom_tpu.serve` — the stdin/file micro-server.

Not a network server (that is a frontend's job); this is the operational
harness for DRIVING the serving stack — warmup, admission, early exit,
telemetry — from a shell or a CI job, the same way train/cli.py drives the
trainer. Requests come from `--synthetic N` (seeded gaussian images — the
reproducible load generator) or `--requests FILE|-` (JSON lines
`{"id": ..., "seed": ...}`; images are generated from the seed, so request
files stay bytes not tensors). Every response, dispatch, warmup, and shed
lands as a schema-v3 record in the metrics stream — the output of a serve
run lints with `python -m glom_tpu.telemetry FILE` like any other artifact
of record, and CI runs exactly that smoke.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from typing import Iterable, Tuple


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m glom_tpu.serve",
        description="GLOM batched-inference micro-server (docs/SERVING.md)",
    )
    p.add_argument("--preset", default="mnist", help="see glom_tpu.utils.presets")
    p.add_argument(
        "--synthetic", type=int, default=None, metavar="N",
        help="serve N seeded synthetic requests (the reproducible load)",
    )
    p.add_argument(
        "--requests", default=None, metavar="FILE",
        help="JSONL request source ('-' = stdin): {\"id\":..., \"seed\":...}",
    )
    p.add_argument(
        "--iters", default=None,
        help="forward iteration budget: an int, or 'auto' for consensus "
        "early exit (serve/early_exit)",
    )
    p.add_argument(
        "--exit-threshold", type=float, default=None, metavar="D",
        help="iters=auto: exit once no level's agreement moves more than D "
        "between iterations (0 disables the exit — full budget always runs)",
    )
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--max-delay-ms", type=float, default=None)
    p.add_argument("--queue-depth", type=int, default=None)
    p.add_argument(
        "--buckets", default=None, metavar="B1,B2,...",
        help="ascending batch buckets to precompile (default: preset's)",
    )
    p.add_argument(
        "--no-warmup", action="store_true",
        help="skip the AOT warmup (buckets then compile on first miss — "
        "the latency cliff warmup exists to remove; for A/B only)",
    )
    p.add_argument(
        "--ladder", action="store_true",
        help="enable the degradation ladder (glom_tpu/resilience/ladder): "
        "under queue pressure or a flapping backend, step down capped-iters "
        "-> capped-buckets -> shed instead of shedding outright "
        "(docs/RESILIENCE.md; one ladder per engine)",
    )
    p.add_argument(
        "--engines", type=int, default=1, metavar="N",
        help="multi-engine fan-out: N InferenceEngines (shared params) "
        "behind one shared-admission batcher, one worker per engine; a "
        "failing engine's batches re-dispatch to its siblings",
    )
    p.add_argument(
        "--mesh-data", type=int, default=None, metavar="D",
        help="serve mesh: shard every bucket's batch rows over a D-way "
        "'data' axis (parallel/serve_mesh.py; buckets must divide by D)",
    )
    p.add_argument(
        "--mesh-seq", type=int, default=None, metavar="S",
        help="serve mesh: shard the patch axis over an S-way 'seq' axis",
    )
    p.add_argument(
        "--quorum", type=float, default=None, metavar="Q",
        help="iters=auto: exit the bucket once ceil(Q * n_valid) valid "
        "rows have individually converged (two-tier early exit; 1.0 = all)",
    )
    p.add_argument(
        "--max-continuations", type=int, default=None, metavar="M",
        help="re-bucket unconverged stragglers (warm state, remaining "
        "budget) up to M hops through the continuation queue; 0 disables",
    )
    p.add_argument(
        "--kill-engine", default=None, metavar="IDX:after=K[,until=M]",
        help="CHAOS: fail engine IDX's dispatches from its K-th call on "
        "(a seeded FaultPlan dispatch_fault — every injection a stamped "
        "'fault' event), so the kill-serve scenario can validate failover "
        "from the evidence trail (docs/RESILIENCE.md). ',until=M' bounds "
        "the fault window — calls from M on succeed again, the recovered-"
        "replica shape the rejoin-serve scenario drives",
    )
    p.add_argument(
        "--rejoin", type=int, default=None, metavar="N",
        help="re-admit a dead engine after N consecutive successful "
        "probation health dispatches (stamped engine_rejoin; "
        "docs/RESILIENCE.md). Default: preset's rejoin_threshold (0 = "
        "death stays terminal)",
    )
    p.add_argument(
        "--rejoin-interval-ms", type=float, default=None, metavar="MS",
        help="pace the probation health dispatches (default: preset's)",
    )
    p.add_argument(
        "--streams", type=int, default=None, metavar="S",
        help="synthetic mode: spread requests over S temporal STREAMS — "
        "each request is a perturbed frame of its stream's base image and "
        "carries session id 's<k>', so the warm-start column cache "
        "(--column-cache-bytes) serves frame t+1 from frame t's converged "
        "columns (docs/SERVING.md, Streaming)",
    )
    p.add_argument(
        "--column-cache-bytes", type=int, default=None, metavar="B",
        help="session column-cache HBM budget in bytes (LRU eviction; "
        "0 disables streaming warm-start). Default: preset's",
    )
    p.add_argument(
        "--column-cache-ttl", type=float, default=None, metavar="S",
        help="expire a quiet stream's cached columns after S seconds",
    )
    p.add_argument(
        "--request-gap-ms", type=float, default=0.0, metavar="G",
        help="pace request submission G ms apart (0 = submit as fast as "
        "admission allows) — chaos scenarios use it to keep traffic "
        "flowing across a fault window",
    )
    p.add_argument(
        "--dispatch-retries", type=int, default=None, metavar="N",
        help="transient dispatch failures retry up to N times with backoff "
        "(watchdog-aware: a DOWN backend never retries; default: preset's)",
    )
    p.add_argument("--out", default=None, help="JSONL metrics path")
    p.add_argument(
        "--flight-recorder", default=None, metavar="DIR",
        help="crash flight recorder over the serve event stream",
    )
    p.add_argument(
        "--elastic", action="store_true",
        help="SLO-driven elastic serving (serve/elastic.py, "
        "docs/SERVING.md): run the Autoscaler control loop — scale OUT "
        "spawns a fully-warmed engine replica at runtime (admission "
        "opens only after precompile), scale IN gracefully drains the "
        "least-loaded engine (migrate cache sessions, release devices). "
        "The fleet starts at --min-engines; --engines is ignored",
    )
    p.add_argument(
        "--min-engines", type=int, default=None, metavar="N",
        help="elastic: the fleet never drains below N (default preset's)",
    )
    p.add_argument(
        "--max-engines", type=int, default=None, metavar="N",
        help="elastic: the fleet never grows past N (default preset's)",
    )
    p.add_argument(
        "--elastic-low-water", type=float, default=None, metavar="H",
        help="scale OUT when worst eligible headroom sits below H for "
        "the dwell (default preset's)",
    )
    p.add_argument(
        "--elastic-high-water", type=float, default=None, metavar="H",
        help="scale IN when worst eligible headroom sits above H for "
        "the dwell (default preset's)",
    )
    p.add_argument(
        "--elastic-dwell", type=float, default=None, metavar="S",
        help="min-dwell hysteresis: a water-mark condition must hold "
        "continuously this long before it may act",
    )
    p.add_argument(
        "--elastic-cooldown", type=float, default=None, metavar="S",
        help="post-action cooldown before the next decision",
    )
    p.add_argument(
        "--elastic-interval", type=float, default=None, metavar="S",
        help="control-tick cadence (capacity records are emitted live "
        "each tick)",
    )
    p.add_argument(
        "--elastic-window", type=float, default=None, metavar="S",
        help="signal window shared by the policy and its SLO monitor "
        "(breaches age out of it; shorter = faster post-spike recovery)",
    )
    p.add_argument(
        "--elastic-p99-ms", type=float, default=None, metavar="MS",
        help="arm the in-process SLO monitor's p99 rule: a windowed "
        "breach forces scale-out and vetoes scale-in",
    )
    p.add_argument(
        "--elastic-shed-rate", type=float, default=None, metavar="R",
        help="arm the shed-rate SLO rule (same precedence as p99)",
    )
    p.add_argument(
        "--elastic-settle", type=float, default=0.0, metavar="S",
        help="after the last ticket resolves, keep the loop running up "
        "to S seconds or until a scale-in lands — the ramp scenario's "
        "deterministic window for the post-spike drain",
    )
    p.add_argument(
        "--ramp", default=None, metavar="N1xG1,N2xG2,...",
        help="offered-load RAMP traffic instead of --synthetic: each "
        "phase submits N seeded synthetic requests paced G ms apart "
        "(e.g. '6x120,48x0,10x150' = low, spike, low) — the chaos "
        "ramp-serve scenario's traffic shape (docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--replay", default=None, metavar="FILE",
        help="replay a recorded workload artifact (serve/workload.py, "
        "docs/SERVING.md 'Record and replay'): re-offer its requests "
        "with faithful inter-arrival pacing and session structure — "
        "the fourth traffic source, exclusive with the others",
    )
    p.add_argument(
        "--replay-time-scale", type=float, default=1.0, metavar="X",
        help="stretch (>1) or compress (<1) the replayed inter-arrival "
        "gaps (1.0 = as recorded)",
    )
    p.add_argument(
        "--record-workload", default=None, metavar="FILE",
        help="record this run's offered traffic as a schema-v9 workload "
        "artifact (arrival time, shape signature, session, outcome) — "
        "replayable later with --replay",
    )
    p.add_argument(
        "--forecast", action="store_true",
        help="emit scored short-horizon 'forecast' records over the "
        "live arrival rate plus a spawn-lead-time model "
        "(telemetry/forecast.py): every window stamps "
        "predicted-vs-realized forecast_abs_err",
    )
    p.add_argument(
        "--elastic-anticipatory", action="store_true",
        help="elastic: act on PREDICTED load at now + spawn lead time "
        "instead of waiting for live breaches — the policy consumes the "
        "forecaster's latest scored window plus the spawn-lead-time "
        "quantile, and every decision is stamped as a schema-v10 "
        "'decision' record carrying its full evidence bundle "
        "(auditable with `python -m glom_tpu.telemetry audit`). "
        "Implies --forecast",
    )
    p.add_argument(
        "--elastic-target-utilization", type=float, default=None,
        metavar="U",
        help="anticipatory: scale out when predicted arrival rate "
        "exceeds U * fleet service rate (0 < U <= 1; default preset's)",
    )
    p.add_argument(
        "--warm-pool", type=int, default=None, metavar="N",
        help="elastic: hold N pre-spawned, precompiled spare engines "
        "OUTSIDE admission; scale-out promotes a spare (milliseconds) "
        "instead of paying a cold spawn, scale-in demotes the drained "
        "engine back into the pool. Every promotion/demotion is stamped "
        "with its owning decision_id",
    )
    p.add_argument(
        "--slo-class", action="append", default=None, metavar="SPEC",
        dest="slo_class",
        help="declare one SLO class (repeatable): "
        "'name:weight=W,p99_ms=MS,shed_rate=R,queue_depth=N' — e.g. "
        "--slo-class premium:weight=8,p99_ms=150 --slo-class batch:"
        "weight=1. Declaring classes arms the weighted-fair admission "
        "scheduler, class-aware degradation/shed, and per-class "
        "telemetry (serve/qos.py, docs/SERVING.md 'SLO classes')",
    )
    p.add_argument(
        "--slo-default-class", default=None, metavar="NAME",
        help="class for unclassed submits (default: 'standard' when "
        "declared, else the highest-weight class)",
    )
    p.add_argument(
        "--slo-shed-order", default=None, metavar="C1,C2,...",
        help="override the shed order (first = first to shed/degrade; "
        "must be a permutation of the declared classes; default: "
        "ascending weight)",
    )
    p.add_argument(
        "--slo-starvation-floor", type=float, default=None, metavar="F",
        help="guaranteed served fraction per non-top class under strict "
        "priority (default 0.05): each backlogged lower class banks F "
        "credit per pick and preempts at a whole owed pick",
    )
    p.add_argument(
        "--husk-max", type=int, default=None, metavar="N",
        help="elastic: retain at most N drained-engine evidence husks "
        "in the summary (oldest retire into a stamped "
        "engine_husk_retired record; default: retain all)",
    )
    p.add_argument(
        "--husk-max-age", type=float, default=None, metavar="S",
        help="elastic: retire a drained husk S seconds after its drain "
        "(default: retain forever)",
    )
    return p


def parse_ramp(spec: str):
    """'6x120,48x0,10x150' -> [(6, 0.12), (48, 0.0), (10, 0.15)] —
    (requests, per-request gap seconds) per phase. Loud on malformed
    phases (a typo'd ramp that silently serves nothing is worse than
    none)."""
    phases = []
    for part in spec.split(","):
        n_s, sep, gap_s = part.partition("x")
        if not sep:
            raise ValueError(
                f"--ramp phase {part!r}: expected NxGAP_MS"
            )
        n, gap = int(n_s), float(gap_s)
        if n < 1 or gap < 0:
            raise ValueError(
                f"--ramp phase {part!r}: need N >= 1 and GAP_MS >= 0"
            )
        phases.append((n, gap / 1e3))
    if not phases:
        raise ValueError(f"--ramp {spec!r}: no phases")
    return phases


def _req_source(args) -> Iterable[Tuple[object, int, object]]:
    """(request id, seed, session id) triples from --synthetic or
    --requests. Synthetic with --streams S deals requests round-robin
    over S sessions ('s0'..'s{S-1}'); request files carry an optional
    "session" field per line."""
    if args.synthetic is not None:
        streams = args.streams or 0
        for i in range(args.synthetic):
            session = f"s{i % streams}" if streams > 0 else None
            yield i, i, session
        return
    fh = sys.stdin if args.requests == "-" else open(args.requests)
    try:
        for line in fh:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            rec = json.loads(line)
            yield rec.get("id"), int(rec.get("seed", 0)), rec.get("session")
    finally:
        if fh is not sys.stdin:
            fh.close()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    n_sources = sum(
        x is not None
        for x in (args.synthetic, args.requests, args.ramp, args.replay)
    )
    if n_sources != 1:
        print(
            "exactly one of --synthetic N, --requests FILE, "
            "--ramp N1xG1,..., or --replay FILE required",
            file=sys.stderr,
        )
        return 2
    ramp_phases = None
    if args.ramp is not None:
        try:
            ramp_phases = parse_ramp(args.ramp)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
    replay_records = None
    if args.replay is not None:
        # Loud before the engines spend a warmup: an unreadable or empty
        # artifact is an argv error, not a mid-run surprise.
        from glom_tpu.serve.workload import load_workload

        try:
            replay_records = load_workload(args.replay)
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return 2

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.serve.events import stamp_serve as serve_rec
    from glom_tpu.utils.metrics import MetricsWriter
    from glom_tpu.utils.presets import get_preset

    preset = get_preset(args.preset)
    cfg = preset.model
    scfg = preset.serve
    overrides = {}
    if args.iters is not None:
        overrides["iters"] = (
            "auto" if args.iters == "auto" else int(args.iters)
        )
    if args.exit_threshold is not None:
        overrides["exit_threshold"] = args.exit_threshold
    if args.max_batch is not None:
        overrides["max_batch"] = args.max_batch
    if args.max_delay_ms is not None:
        overrides["max_delay_ms"] = args.max_delay_ms
    if args.queue_depth is not None:
        overrides["queue_depth"] = args.queue_depth
    if args.buckets is not None:
        overrides["buckets"] = tuple(
            int(b) for b in args.buckets.split(",") if b
        )
    if args.ladder:
        overrides["ladder"] = True
    if args.dispatch_retries is not None:
        overrides["dispatch_retries"] = args.dispatch_retries
    if args.mesh_data is not None:
        overrides["mesh_data"] = args.mesh_data
    if args.mesh_seq is not None:
        overrides["mesh_seq"] = args.mesh_seq
    if args.quorum is not None:
        overrides["exit_quorum"] = args.quorum
    if args.max_continuations is not None:
        overrides["max_continuations"] = args.max_continuations
    if args.rejoin is not None:
        overrides["rejoin_threshold"] = args.rejoin
    if args.rejoin_interval_ms is not None:
        overrides["rejoin_interval_ms"] = args.rejoin_interval_ms
    if args.column_cache_bytes is not None:
        overrides["column_cache_bytes"] = args.column_cache_bytes
    if args.column_cache_ttl is not None:
        overrides["column_cache_ttl_s"] = args.column_cache_ttl
    if args.elastic:
        overrides["elastic"] = True
    for flag, field in (
        ("min_engines", "min_engines"),
        ("max_engines", "max_engines"),
        ("elastic_low_water", "elastic_low_water"),
        ("elastic_high_water", "elastic_high_water"),
        ("elastic_dwell", "elastic_dwell_s"),
        ("elastic_cooldown", "elastic_cooldown_s"),
        ("elastic_interval", "elastic_interval_s"),
        ("elastic_window", "elastic_window_s"),
        ("elastic_p99_ms", "elastic_p99_ms"),
        ("elastic_shed_rate", "elastic_shed_rate"),
        ("husk_max", "husk_max"),
        ("husk_max_age", "husk_max_age_s"),
        ("elastic_target_utilization", "elastic_target_utilization"),
        ("warm_pool", "warm_pool"),
    ):
        v = getattr(args, flag)
        if v is not None:
            overrides[field] = v
    if args.elastic_anticipatory:
        overrides["elastic_anticipatory"] = True
    if args.slo_class:
        overrides["slo_classes"] = tuple(args.slo_class)
    if args.slo_default_class is not None:
        overrides["slo_default_class"] = args.slo_default_class
    if args.slo_shed_order is not None:
        overrides["slo_shed_order"] = tuple(
            c.strip() for c in args.slo_shed_order.split(",") if c.strip()
        )
    if args.slo_starvation_floor is not None:
        overrides["slo_starvation_floor"] = args.slo_starvation_floor
    if overrides:
        scfg = dataclasses.replace(scfg, **overrides)
    if args.engines < 1:
        print("--engines must be >= 1", file=sys.stderr)
        return 2

    writer = MetricsWriter(args.out, echo=True)
    fr = None
    if args.flight_recorder:
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )

        fr = FlightRecorder(args.flight_recorder)
        fr.install_process_hooks()
        set_global_flight_recorder(fr)

    try:
        # One params init shared by every engine replica (fan-out serves
        # ONE model), one engine per replica. A serve mesh partitions the
        # device pool into one contiguous group per engine
        # (parallel/runtime.make_engine_meshes).
        import jax

        from glom_tpu.models.core import init_glom

        params = init_glom(jax.random.PRNGKey(0), cfg)
        # Elastic mode starts at the policy floor (--engines is the
        # STATIC fleet size); scale-out spawns the rest at runtime.
        n_init = scfg.min_engines if scfg.elastic else args.engines
        if scfg.mesh_data > 1 or scfg.mesh_seq > 1:
            from glom_tpu.parallel.runtime import make_engine_meshes

            meshes = make_engine_meshes(scfg, n_init)
        else:
            meshes = [None] * n_init
        kill_idx, kill_plan = None, None
        if args.kill_engine is not None:
            # "IDX:after=K": engine IDX's dispatch hook raises on every
            # attempt from index K on — the in-process analog of a dead
            # replica, stamped per injection so the chaos driver can
            # reconcile failover against the injected ground truth.
            from glom_tpu.resilience.faults import FaultPlan, dispatch_fault

            idx_s, _, window = args.kill_engine.partition(":after=")
            kill_idx = int(idx_s)
            if not 0 <= kill_idx < n_init:
                print(f"--kill-engine index {kill_idx} outside 0.."
                      f"{n_init - 1}", file=sys.stderr)
                return 2
            after_s, _, until_s = window.partition(",until=")
            kill_plan = FaultPlan(writer=writer)
            kill_plan.register(
                f"engine{kill_idx}-dispatch",
                rate=1.0,
                start=int(after_s or 0),
                stop=int(until_s) if until_s else None,
                fault="engine-dead",
            )
        engines = []
        for i in range(n_init):
            hook = None
            if kill_plan is not None and i == kill_idx:
                hook = dispatch_fault(kill_plan, f"engine{i}-dispatch")
            engines.append(
                InferenceEngine(
                    cfg, scfg, params=params, writer=writer,
                    mesh=meshes[i], name=f"engine{i}", fault_hook=hook,
                )
            )
        degraded_iters = None
        if scfg.ladder:
            degraded_iters = (
                scfg.degraded_iters
                if scfg.degraded_iters is not None
                else max(1, cfg.default_iters // 2)
            )
        if not args.no_warmup:
            for engine in engines:
                engine.warmup()
                if degraded_iters is not None:
                    # Pre-warm the capped-iters route too: the first
                    # degraded dispatch must not pay a mid-traffic compile
                    # on top of the pressure that degraded it.
                    engine.warmup(iters_override=degraded_iters)

        shape = (cfg.channels, cfg.image_size, cfg.image_size)
        rng_img = lambda seed: np.random.default_rng(seed).normal(
            size=shape
        ).astype(np.float32)

        def frame_img(seed, session):
            # A stream's frames are small perturbations of ITS base image
            # (the temporal-coherence assumption the column cache
            # exploits); stateless requests stay pure seeded gaussians.
            if session is None:
                return rng_img(seed)
            import zlib  # deterministic across processes, unlike hash()

            base = rng_img(zlib.crc32(str(session).encode()) & 0x7FFFFFFF)
            return base + 0.05 * rng_img((1 << 20) + seed)

        def req_plan():
            """(rid, seed, session, gap_s) per request: the flat
            --synthetic/--requests source at the constant
            --request-gap-ms, or the --ramp phases at each phase's own
            pace (a stamped note marks every phase boundary, so the
            chaos driver can split its p99 windows on evidence)."""
            flat_gap = max(0.0, args.request_gap_ms) / 1e3
            if ramp_phases is None:
                for rid, seed, session in _req_source(args):
                    yield rid, seed, session, flat_gap
                return
            streams = args.streams or 0
            i = 0
            for phase, (n, gap) in enumerate(ramp_phases):
                writer.write(
                    serve_rec(
                        {
                            "event": "ramp_phase",
                            "phase": phase,
                            "n_requests": n,
                            "gap_ms": round(1e3 * gap, 3),
                        }
                    )
                )
                for _ in range(n):
                    session = f"s{i % streams}" if streams > 0 else None
                    yield i, i, session, gap
                    i += 1

        served = failed = 0
        scaler = None
        with DynamicBatcher(engines=engines, writer=writer) as batcher:
            recorder = None
            if args.record_workload is not None:
                from glom_tpu.serve.workload import WorkloadRecorder

                recorder = WorkloadRecorder().attach(batcher)
            forecaster = None
            if args.forecast or scfg.elastic_anticipatory:
                # Anticipatory scaling FEEDS on the forecaster — a
                # policy told to act on predicted load with no
                # prediction source would silently degrade to reactive
                # forever, so --elastic-anticipatory implies --forecast.
                from glom_tpu.telemetry.forecast import ForecastEmitter
                from glom_tpu.tracing.flight import write_or_observe

                batcher.enable_admission_events()
                forecaster = ForecastEmitter(
                    lambda r: write_or_observe(writer, r)
                )
                batcher.add_event_tap(forecaster.tap)
            if scfg.elastic:
                from glom_tpu.serve.elastic import (
                    Autoscaler,
                    resolve_policy,
                )

                spawn_seq = [len(engines)]

                def engine_factory():
                    # A brand-new replica on its OWN device group (the
                    # next contiguous partition slot —
                    # parallel/runtime.engine_mesh_for); shared params —
                    # fan-out serves one model. The autoscaler runs
                    # warmup() before registration; a group-exhausted
                    # device pool raises into its spawn_rollback path.
                    i = spawn_seq[0]
                    mesh = None
                    if scfg.mesh_data > 1 or scfg.mesh_seq > 1:
                        from glom_tpu.parallel.runtime import (
                            engine_mesh_for,
                        )

                        mesh = engine_mesh_for(scfg, i)
                    eng = InferenceEngine(
                        cfg, scfg, params=params, writer=writer,
                        mesh=mesh, name=f"engine{i}",
                    )
                    spawn_seq[0] += 1
                    return eng

                rules = {}
                if scfg.elastic_p99_ms is not None:
                    rules["p99_ms"] = scfg.elastic_p99_ms
                if scfg.elastic_shed_rate is not None:
                    rules["shed_rate"] = scfg.elastic_shed_rate
                if scfg.slo_classes:
                    # Each class's declared targets become class-scoped
                    # monitor rules ("p99_ms[premium]"); low-class
                    # breaches are recorded but non-binding — the
                    # policy's low_classes filter (serve/qos.py).
                    from glom_tpu.serve.qos import (
                        class_slo_rules,
                        resolve_slo_classes,
                    )

                    spec = resolve_slo_classes(scfg)
                    if spec is not None:
                        rules.update(class_slo_rules(spec))
                scaler = Autoscaler(
                    batcher, engine_factory,
                    policy=resolve_policy(scfg),
                    rules=rules,
                    writer=writer,
                    interval_s=scfg.elastic_interval_s,
                    warm_degraded_iters=degraded_iters,
                    forecast=forecaster,
                    warm_pool=scfg.warm_pool,
                ).start()
            tickets = []
            if replay_records is not None:
                from glom_tpu.serve import workload as wl

                def offer(rec, i):
                    rid = rec.get("request_id", i)
                    try:
                        tickets.append(
                            (rid, batcher.submit(
                                wl.synth_input(rec, i),
                                session_id=rec.get("session"),
                                # v11: re-offer the recorded tenant class
                                # (null = classless, exactly as captured).
                                slo_class=rec.get("slo_class"),
                            ))
                        )
                    except ShedError as e:
                        writer.write(
                            serve_rec(
                                {
                                    "event": "response",
                                    "id": rid,
                                    "ok": False,
                                    "reason": (
                                        f"{type(e).__name__}: {e}"[:200]
                                    ),
                                    "trace_id": getattr(
                                        e, "detail", {}
                                    ).get("trace_id"),
                                }
                            )
                        )
                        raise  # replay counts it as shed and drives on

                stats = wl.replay(
                    replay_records, offer,
                    time_scale=args.replay_time_scale,
                )
                failed += stats["n_shed"]
                writer.write(
                    serve_rec(
                        {
                            "event": "replay_summary",
                            "source": args.replay,
                            "time_scale": args.replay_time_scale,
                            **stats,
                        }
                    )
                )
            else:
                for rid, seed, session, gap_s in req_plan():
                    if gap_s and tickets:
                        time.sleep(gap_s)
                    try:
                        tickets.append(
                            (rid, batcher.submit(
                                frame_img(seed, session), session_id=session
                            ))
                        )
                    except ShedError as e:
                        failed += 1
                        # The shed exception's detail carries the minted
                        # trace_id (serve/batcher.submit), so even a
                        # rejected request's response joins its trace's
                        # shed leaf.
                        writer.write(
                            serve_rec(
                                {
                                    "event": "response",
                                    "id": rid,
                                    "ok": False,
                                    "reason": (
                                        f"{type(e).__name__}: {e}"[:200]
                                    ),
                                    "trace_id": getattr(
                                        e, "detail", {}
                                    ).get("trace_id"),
                                }
                            )
                        )
            for rid, ticket in tickets:
                try:
                    levels, iters_run, latency_s = ticket.result(timeout=300.0)
                except Exception as e:  # noqa: BLE001 — per-request record
                    failed += 1
                    writer.write(
                        serve_rec(
                            {
                                "event": "response",
                                "id": rid,
                                "ok": False,
                                "reason": f"{type(e).__name__}: {e}"[:200],
                                "trace_id": ticket.trace_id,
                                "parent_span": ticket.span_id,
                            }
                        )
                    )
                    continue
                served += 1
                # The response is the trace's user-visible leaf: it
                # parents to the submit root (the serve-side resolve leaf
                # carries the per-hop conservation totals).
                writer.write(
                    serve_rec(
                        {
                            "event": "response",
                            "id": rid,
                            "ok": True,
                            "latency_ms": round(1e3 * latency_s, 3),
                            "iters_run": iters_run,
                            "top_level_norm": round(
                                float(np.linalg.norm(levels[:, -1]) / levels.shape[0]),
                                4,
                            ),
                            "trace_id": ticket.trace_id,
                            "parent_span": ticket.span_id,
                        }
                    )
                )
            if scaler is not None:
                # The settle window: the ramp's post-spike drain lands
                # here (bounded — the loop exits the moment a scale-in
                # completes, so an idle fleet never waits the full S).
                deadline = time.monotonic() + max(0.0, args.elastic_settle)
                while time.monotonic() < deadline:
                    if scaler.record()["n_scale_ins"] >= 1:
                        break
                    time.sleep(0.05)
                scaler.stop()
            if forecaster is not None:
                # Flush the final partial window + lead-time model while
                # the stream is still open: the run's LAST traffic still
                # scores the forecast.
                forecaster.close()
            writer.write(serve_rec(batcher.summary_record()))
            for rec in batcher.span_records():
                writer.write(rec)
            if recorder is not None:
                n_rec = recorder.write(
                    args.record_workload,
                    source=f"serve-cli:{args.preset}",
                )
                writer.write(
                    serve_rec(
                        {
                            "event": "workload_recorded",
                            "path": args.record_workload,
                            "n_requests": n_rec,
                            **recorder.summary(),
                        }
                    )
                )
        for engine in batcher.engines:
            for rec in engine.stats_records():
                writer.write(serve_rec(rec))
            for rec in engine.collective_time_records():
                # Already stamped kind "collective_time" (sharded route
                # with timing on; empty otherwise) — the micro-server's
                # stream carries the wall-time evidence like any log.
                writer.write(rec)
        return 0 if failed == 0 and served > 0 else 1
    finally:
        writer.close()
        if fr is not None:
            fr.dump("run-end")
            from glom_tpu.tracing.flight import set_global_flight_recorder

            set_global_flight_recorder(None)


if __name__ == "__main__":
    sys.exit(main())
