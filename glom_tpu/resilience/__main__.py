"""`python -m glom_tpu.resilience` — the chaos scenario driver."""

import sys

from glom_tpu.resilience.chaos import main

if __name__ == "__main__":
    sys.exit(main())
