"""Retry-with-backoff for transient dispatch failures, watchdog-aware.

The serving stack's failure discipline so far was binary: a dispatch
exception failed its batch, a down backend shed. That is right for a DEAD
backend — retrying into it is the round-5 hang — and wrong for a FLAPPING
one, where the gap closes in seconds and a retry converts a failed batch
into a served one. The watchdog already distinguishes the two states;
RetryPolicy is where that distinction becomes behavior:

  * backend_state == "down"  -> fail FAST, no retry (the shed path owns it);
  * "up" / "flapping" / "unknown" -> bounded exponential backoff, each
    retry stamped as a schema-v4 "recovery" event (action
    "dispatch-retry"), and a success after retries stamped as
    "dispatch-recovered" — a flap survived on the record, not silently.

Nonretryable exception types (caller bugs: ValueError/TypeError by
default) raise immediately; so do KeyboardInterrupt/SystemExit, which the
policy never catches. Thread-safe: the counters ride one lock — the
engine is called from the batcher worker while summaries read from the
caller's thread (the lockset contract, docs/ANALYSIS.md).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple, Type

NONRETRYABLE_DEFAULT: Tuple[Type[BaseException], ...] = (ValueError, TypeError)


def validate_backoff(
    backoff_s: float, backoff_factor: float, backoff_max_s: float
) -> None:
    """THE bounded-exponential-backoff parameter contract — one
    definition for every policy that backs off (RetryPolicy here,
    train/supervise.TrainSupervisor): a change to what 'valid backoff'
    means must not be able to diverge between them."""
    if backoff_s < 0 or backoff_max_s < 0 or backoff_factor < 1.0:
        raise ValueError(
            f"backoff_s={backoff_s} backoff_max_s={backoff_max_s} "
            f"backoff_factor={backoff_factor}: backoffs must be >= 0 "
            "and the factor >= 1"
        )


def next_backoff(
    backoff_s: float, backoff_factor: float, backoff_max_s: float, n: int
) -> float:
    """The n-th (0-based) delay of the bounded exponential schedule:
    min(backoff_s * factor**n, backoff_max_s). Shared by RetryPolicy and
    TrainSupervisor so the growth/cap semantics cannot silently fork."""
    return min(backoff_s * backoff_factor ** n, backoff_max_s)


class RetryPolicy:
    """Bounded exponential-backoff retry around one callable attempt."""

    def __init__(
        self,
        *,
        retries: int = 2,
        backoff_s: float = 0.025,
        backoff_factor: float = 2.0,
        backoff_max_s: float = 1.0,
        nonretryable: Optional[Tuple[Type[BaseException], ...]] = None,
        writer=None,
        sleep: Callable[[float], None] = time.sleep,
        site: str = "dispatch",
    ):
        if retries < 0:
            raise ValueError(f"retries {retries} must be >= 0")
        validate_backoff(backoff_s, backoff_factor, backoff_max_s)
        self.retries = retries
        self.backoff_s = backoff_s
        self.backoff_factor = backoff_factor
        self.backoff_max_s = backoff_max_s
        self.nonretryable = (
            nonretryable if nonretryable is not None else NONRETRYABLE_DEFAULT
        )
        self.writer = writer
        self.site = site
        self._sleep = sleep
        self._lock = threading.Lock()
        self._n_calls = 0
        self._n_retries = 0
        self._n_recovered = 0
        self._n_gave_up = 0
        self._n_fast_failed = 0

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        from glom_tpu.resilience.faults import emit_recovery

        emit_recovery(self.writer, rec)

    def record(self) -> dict:
        """Counter snapshot for summary records (one consistent read)."""
        with self._lock:
            return {
                "retry_site": self.site,
                "n_calls": self._n_calls,
                "n_retries": self._n_retries,
                "n_recovered": self._n_recovered,
                "n_gave_up": self._n_gave_up,
                "n_fast_failed": self._n_fast_failed,
            }

    # -- the loop ----------------------------------------------------------

    def run(self, attempt: Callable[[], object], **context):
        """Call `attempt` until it returns, the budget exhausts, or the
        backend goes down. `context` (bucket, n_valid, ...) rides every
        stamped recovery event."""
        from glom_tpu.telemetry.watchdog import backend_record

        with self._lock:
            self._n_calls += 1
        tries = 0
        while True:
            try:
                out = attempt()
            except self.nonretryable:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                state = backend_record().get("backend_state", "unknown")
                if state == "down":
                    # Never retry into a dead backend: the watchdog says
                    # the gap is not closing, and the caller's shed path
                    # (fast-fail + stamped evidence) owns this case.
                    with self._lock:
                        self._n_fast_failed += 1
                    raise
                if tries >= self.retries:
                    with self._lock:
                        self._n_gave_up += 1
                    raise
                tries += 1
                with self._lock:
                    self._n_retries += 1
                backoff = next_backoff(
                    self.backoff_s, self.backoff_factor,
                    self.backoff_max_s, tries - 1,
                )
                self._emit(
                    {
                        "action": "dispatch-retry",
                        "site": self.site,
                        "attempt": tries,
                        "retries_budget": self.retries,
                        "backoff_s": round(backoff, 4),
                        "backend_state": state,
                        "exception": f"{type(e).__name__}: {e}"[:300],
                        **context,
                    }
                )
                if backoff > 0:
                    self._sleep(backoff)
                continue
            if tries:
                with self._lock:
                    self._n_recovered += 1
                self._emit(
                    {
                        "action": "dispatch-recovered",
                        "site": self.site,
                        "attempts": tries + 1,
                        **context,
                    }
                )
            return out
