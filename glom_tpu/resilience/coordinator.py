"""Pod-coordinated preemption: the save barrier, gang supervision, and
the cross-host plumbing they share (docs/RESILIENCE.md).

The SIGTERM grace-window checkpoint (tracing/flight.set_checkpoint_hook →
utils/checkpoint.preemption_save) saves SINGLE-HOST state. On a
multi-process pod — exactly the topology the ZeRO-sharded trainer exists
for — an uncoordinated grace save leaves hosts committed at different
steps, and the resume is silently inconsistent: each host restores its
own newest step and the gang trains from a state no single step ever
described. This module closes that gap with three pieces:

  * TWO-PHASE PREEMPTION SAVE BARRIER (PodCoordinator.preemption_barrier)
    — on SIGTERM every host proposes its highest dispatchable step (the
    step its live state can commit), the round commits the MIN over
    proposals, and every host then lands exactly that step inside the
    grace deadline: the host AT the min grace-saves its live state; a
    host already PAST it proves the step is still retained on disk. A
    host that misses the deadline — or whose save fails — aborts the
    round loudly (stamped "barrier" abort, no pod commit marker), so a
    partial pod checkpoint can never masquerade as complete. Every
    phase of every round is a stamped schema "barrier" event.

  * CROSS-HOST RESTORE RECONCILIATION — utils/checkpoint.CheckpointManager
    grows a pod mode (`pod_peers=[...]`): restore(None) walks this
    host's steps newest-first and only hands out a step whose per-host
    manifests are ALL valid; a half-committed step (torn, missing, or
    checksum-failed on any host) is quarantined on EVERY host — the
    multi-host twin of the PR 6 torn-step path — with the decision
    stamped (recovery action "quarantine-half-step").

  * GANG SUPERVISION (signal_gang_stop / gang_stop_requested /
    gang_barrier, wired through train/supervise.fit_supervised's `gang=`
    seam) — one host's crash signals a gang-wide stop; every member
    raises GangRestart at its next checkpoint-span boundary, the gang
    rendezvous at the restart barrier, and every member resumes from the
    reconciled common step.

TRANSPORT: rendezvous rides a SHARED DIRECTORY (DirectoryTransport — one
atomically-written JSON message file per host per phase), so the whole
layer runs in CPU tier-1 with plain subprocesses or threads; real pods
swap in JaxDistributedTransport (the jax.distributed key-value store)
behind the same three-method interface. Message posts carry a fault-hook
seam (resilience/faults.message_loss / barrier_delay) so barrier-message
loss and deadline overrun are injectable, deterministic, and stamped.

Step-drift contract: "highest dispatchable step" is the step a host's
live state can commit RIGHT NOW. In a real lockstep pod the collectives
bound drift to the one in-flight step; in the chaos harness (independent
subprocesses) drift is bounded by per-step checkpointing + retention —
a host past the committed min that no longer RETAINS that step cannot
satisfy the round and aborts it loudly (raise --checkpoint-keep).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

from glom_tpu.telemetry import schema


# The persistent per-lifetime "this member finished every step" flag:
# gang-restart barriers excuse done hosts from arrival (a finished member
# never rendezvous again). A relaunched host's own stale flag is purged
# by DirectoryTransport's construction-time cleanup.
GANG_DONE_ROUND = "gang-done"


class BarrierAbort(RuntimeError):
    """A coordination round could not complete: deadline passed with
    hosts missing, a peer aborted, or this host's own save failed. The
    abort is stamped BEFORE this raises — a silent abort would be the
    exact partial-pod-checkpoint hazard the barrier exists to prevent."""

    def __init__(self, message: str, **detail):
        super().__init__(message)
        self.detail = detail


class GangRestart(RuntimeError):
    """Raised inside a gang member's training loop when a peer signaled a
    gang-wide stop: the supervisor treats it like any failure (restart +
    backoff), so the whole gang falls back to the restart barrier and
    resumes from the reconciled common step together."""


def _emit_barrier(writer, rec: dict) -> dict:
    """Stamp one "barrier" event and deliver writer-else-flight — the
    same routing as emit_fault/emit_recovery, for the new kind."""
    from glom_tpu.tracing.flight import write_or_observe

    stamped = schema.stamp(rec, kind="barrier")
    write_or_observe(writer, stamped)
    return stamped


class DirectoryTransport:
    """Rendezvous over a shared directory: one message = one atomically
    renamed JSON file `<root>/rounds/<round>/<phase>_<host>.json`.

    This is the CPU-tier-1 transport (subprocesses or threads on one
    filesystem) AND the degraded-mode transport for real pods whose
    checkpoint storage is already shared. Posts are atomic (temp + fsync
    + rename, via utils.checkpoint.atomic_write_json) so a reader never
    sees a torn message; reads are lock-free directory scans. The
    `fault_hook` seam is how the chaos harness injects barrier-message
    loss (hook returns True → the message is silently dropped) and
    deadline overrun (hook stalls before the write)."""

    def __init__(
        self,
        root,
        host: int,
        n_hosts: int,
        *,
        fault_hook: Optional[Callable[[dict], bool]] = None,
    ):
        if n_hosts < 1:
            raise ValueError(f"n_hosts {n_hosts} must be >= 1")
        if not 0 <= host < n_hosts:
            raise ValueError(f"host {host} outside 0..{n_hosts - 1}")
        self.root = Path(root)
        self.host = host
        self.n_hosts = n_hosts
        self.fault_hook = fault_hook
        (self.root / "rounds").mkdir(parents=True, exist_ok=True)
        # Round ids are derived from the RESUME step — the one value
        # hosts agree on without communicating — so a relaunch after an
        # aborted (or zero-progress) round reuses the id. A fresh
        # process must therefore never own stale messages: a leftover
        # abort would poison every future round with this id, and a
        # leftover propose/saved could complete one without us. Each
        # host deletes ITS OWN messages at construction (= process
        # start, before any round); peers' files are theirs to clean.
        # Durable pod_commit markers live at the root, not under
        # rounds/, and are deliberately kept.
        for stale in (self.root / "rounds").glob(f"*/*_{host}.json"):
            try:
                stale.unlink()
            except OSError:
                pass

    def _round_dir(self, round_id: str) -> Path:
        return self.root / "rounds" / round_id

    def post(self, round_id: str, phase: str, payload: dict) -> bool:
        """Post this host's message for (round, phase); returns False when
        the fault hook dropped it (simulated message loss — the poster,
        like a real sender over a lossy link, is not told)."""
        if self.fault_hook is not None and self.fault_hook(
            {"op": "post", "round": round_id, "phase": phase, "host": self.host}
        ):
            return False
        from glom_tpu.utils.checkpoint import atomic_write_json

        rdir = self._round_dir(round_id)
        rdir.mkdir(parents=True, exist_ok=True)
        atomic_write_json(
            rdir / f"{phase}_{self.host}.json",
            {"host": self.host, **payload},
        )
        return True

    def read_all(self, round_id: str, phase: str) -> Dict[int, dict]:
        """{host: payload} for every message posted so far — a partially
        torn directory scan never raises (a message mid-rename simply
        isn't there yet)."""
        out: Dict[int, dict] = {}
        rdir = self._round_dir(round_id)
        if not rdir.is_dir():
            return out
        for p in rdir.glob(f"{phase}_*.json"):
            try:
                host = int(p.stem.rsplit("_", 1)[1])
                with open(p) as fh:
                    out[host] = json.load(fh)
            except (ValueError, OSError, json.JSONDecodeError):
                continue
        return out


class JaxDistributedTransport:
    """The same three-method interface over jax.distributed's key-value
    store — the transport for REAL pods (no shared filesystem needed:
    the TPU coordinator service carries the messages). Construction
    requires jax.distributed.initialize() to have run; the CPU tier-1
    suite never touches this class (DirectoryTransport covers the
    protocol), and the hardware queue's first multi-process window is
    where it earns its keep."""

    def __init__(self, *, timeout_ms: int = 60_000):
        import jax

        state = getattr(
            getattr(jax, "_src", None), "distributed", None
        )
        client = getattr(getattr(state, "global_state", None), "client", None)
        if client is None:  # pragma: no cover — real-pod only
            raise RuntimeError(
                "JaxDistributedTransport requires jax.distributed."
                "initialize() (the multi-process pod runtime); use "
                "DirectoryTransport for single-machine rendezvous"
            )
        self._client = client
        self._timeout_ms = timeout_ms
        self.host = jax.process_index()
        self.n_hosts = jax.process_count()
        self.fault_hook = None

    def post(self, round_id: str, phase: str, payload: dict) -> bool:  # pragma: no cover
        self._client.key_value_set(
            f"glom/{round_id}/{phase}_{self.host}",
            json.dumps({"host": self.host, **payload}),
        )
        return True

    def read_all(self, round_id: str, phase: str) -> Dict[int, dict]:  # pragma: no cover
        out: Dict[int, dict] = {}
        for h in range(self.n_hosts):
            try:
                raw = self._client.key_value_try_get(
                    f"glom/{round_id}/{phase}_{h}"
                )
            except Exception:  # noqa: BLE001 — absent key
                continue
            try:
                out[h] = json.loads(raw)
            except (TypeError, json.JSONDecodeError):
                continue
        return out


class PodCoordinator:
    """Host-side coordination over a transport: the preemption save
    barrier plus the gang-stop/rendezvous primitives fit_supervised's
    gang mode rides. Every decision is a stamped schema event ("barrier"
    for round phases, "recovery" for gang stops), delivered
    writer-else-flight so a dying process still leaves the round's story
    in its flight dump."""

    def __init__(
        self,
        transport,
        *,
        writer=None,
        poll_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if poll_s <= 0:
            raise ValueError(f"poll_s {poll_s} must be > 0")
        self.transport = transport
        self.host = transport.host
        self.n_hosts = transport.n_hosts
        self.writer = writer
        self.poll_s = poll_s
        self._clock = clock
        self._sleep = sleep

    # -- stamping ----------------------------------------------------------

    def _emit(self, phase: str, round_id: str, **detail) -> dict:
        return _emit_barrier(
            self.writer,
            {
                "phase": phase,
                "round": round_id,
                "host": self.host,
                "n_hosts": self.n_hosts,
                "wall_time_s": round(time.time(), 3),
                **detail,
            },
        )

    # -- barrier plumbing --------------------------------------------------

    def _abort(self, round_id: str, reason: str, **detail) -> BarrierAbort:
        """Post + stamp the abort, return the exception for the caller to
        raise. The post is best-effort (the transport may be the thing
        that failed); the stamp always lands locally."""
        try:
            self.transport.post(round_id, "abort", {"reason": reason, **detail})
        except Exception:  # noqa: BLE001 — the stamp still records it
            pass
        self._emit("abort", round_id, reason=reason, **detail)
        return BarrierAbort(
            f"barrier round {round_id} aborted on host {self.host}: {reason}",
            round=round_id, reason=reason, **detail,
        )

    def _wait_all(
        self,
        round_id: str,
        phase: str,
        deadline: float,
        *,
        honor_done: bool = False,
    ) -> Dict[int, dict]:
        """Block until all n_hosts posted (round, phase); raise
        BarrierAbort on a peer abort or on the deadline — stamping which
        hosts were missing, because 'who never answered' is the first
        postmortem question. With honor_done (the gang-restart barriers),
        a host that posted the persistent gang-done flag counts as
        arrived: a member that already finished every step will never
        rendezvous again, and waiting for it would deadlock the
        survivors' recovery."""
        while True:
            # Aborts are read FIRST: a host that limped in late must not
            # declare a round complete that a peer already aborted (the
            # pod commit marker — written only after host 0's own full
            # wait — stays the one completeness authority either way).
            aborts = self.transport.read_all(round_id, "abort")
            peer_aborts = {h: a for h, a in aborts.items() if h != self.host}
            msgs = self.transport.read_all(round_id, phase)
            required = set(range(self.n_hosts))
            if honor_done:
                required -= set(
                    self.transport.read_all(GANG_DONE_ROUND, "done")
                )
                required.add(self.host)  # our own arrival is never excused
            if not peer_aborts and required <= set(msgs):
                return msgs
            if peer_aborts:
                h, a = sorted(peer_aborts.items())[0]
                raise self._abort(
                    round_id,
                    f"peer host {h} aborted: {a.get('reason', '?')}",
                    peer=h, waiting_for=phase,
                )
            if self._clock() >= deadline:
                missing = sorted(required - set(msgs))
                raise self._abort(
                    round_id,
                    f"deadline passed waiting for {phase}",
                    waiting_for=phase, missing=missing,
                )
            self._sleep(self.poll_s)

    # -- the two-phase preemption save barrier -----------------------------

    def preemption_barrier(
        self,
        round_id: str,
        proposal_step: int,
        save_fn: Callable[[int], Any],
        *,
        deadline_s: float = 30.0,
    ) -> int:
        """Run one coordinated grace-save round; returns the committed
        common step. Phase 1: propose `proposal_step` (this host's
        highest dispatchable step) and wait for every host's proposal;
        the round commits the MIN. Phase 2: `save_fn(commit)` must land
        exactly that step on this host (save now, or prove it is still
        retained), then every host acks and — on full acknowledgment —
        host 0 writes the pod commit marker `pod_commit_<step>.json`.
        Any miss (deadline, peer abort, failed save) raises BarrierAbort
        with the abort already stamped and NO commit marker written."""
        deadline = self._clock() + deadline_s
        proposal_step = int(proposal_step)
        self.transport.post(round_id, "propose", {"step": proposal_step})
        self._emit(
            "propose", round_id, step=proposal_step, deadline_s=deadline_s
        )
        proposals = self._wait_all(round_id, "propose", deadline)
        commit = min(int(p["step"]) for p in proposals.values())
        self._emit(
            "commit", round_id, step=commit,
            proposals={str(h): int(p["step"]) for h, p in sorted(proposals.items())},
        )
        try:
            note = save_fn(commit)
        except BaseException as e:  # noqa: BLE001 — aborts the round loudly
            raise self._abort(
                round_id,
                f"save of committed step {commit} failed: "
                f"{type(e).__name__}: {e}"[:300],
                step=commit,
            ) from e
        self.transport.post(round_id, "saved", {"step": commit})
        self._emit("saved", round_id, step=commit, note=str(note or "saved"))
        self._wait_all(round_id, "saved", deadline)
        if self.host == 0:
            marker = {
                "step": commit,
                "round": round_id,
                "n_hosts": self.n_hosts,
                "proposals": {
                    str(h): int(p["step"])
                    for h, p in sorted(proposals.items())
                },
                "wall_time_s": round(time.time(), 3),
            }
            root = getattr(self.transport, "root", None)
            if root is not None:
                from glom_tpu.utils.checkpoint import atomic_write_json

                atomic_write_json(
                    Path(root) / f"pod_commit_{commit}.json", marker
                )
            else:
                # Rootless transports (the jax.distributed KV store)
                # carry the marker as a round message instead; peers
                # read it with read_all(round, "pod-commit").
                self.transport.post(round_id, "pod-commit", marker)
        self._emit("complete", round_id, step=commit)
        return commit

    # -- gang supervision --------------------------------------------------

    def _gang_round(self, epoch: int) -> str:
        return f"gang-e{int(epoch)}"

    def signal_gang_stop(self, epoch: int, reason: str) -> None:
        """One host's failure becomes the gang's restart: post the stop
        flag for this epoch (peers poll it between checkpoint spans) and
        stamp the decision as a recovery event."""
        from glom_tpu.resilience.faults import emit_recovery

        self.transport.post(
            self._gang_round(epoch), "stop", {"reason": str(reason)[:300]}
        )
        emit_recovery(
            self.writer,
            {
                "action": "gang-stop",
                "epoch": int(epoch),
                "host": self.host,
                "reason": str(reason)[:300],
            },
        )

    def gang_stop_requested(self, epoch: int) -> bool:
        return bool(self.transport.read_all(self._gang_round(epoch), "stop"))

    def signal_gang_done(self, steps: int) -> None:
        """This member finished every step and is leaving the gang:
        post the persistent done flag so restart barriers stop waiting
        for a host that will never rendezvous again."""
        self.transport.post(GANG_DONE_ROUND, "done", {"steps": int(steps)})
        self._emit("done", GANG_DONE_ROUND, steps=int(steps))

    def gang_barrier(
        self, name: str, epoch: int, *, deadline_s: float = 30.0
    ) -> None:
        """Rendezvous: every gang member posts arrival for (name, epoch)
        and blocks until all arrived — messages persist, so a late member
        (deeper backoff) sails through an already-full barrier, and a
        member that posted gang-done (finished all its steps) is excused.
        A member that never arrives inside the deadline aborts the round
        loudly (the supervisor's restart budget then decides what
        happens)."""
        round_id = f"{name}-e{int(epoch)}"
        deadline = self._clock() + deadline_s
        self.transport.post(round_id, "arrive", {})
        self._emit("arrive", round_id, epoch=int(epoch))
        self._wait_all(round_id, "arrive", deadline, honor_done=True)
        self._emit("complete", round_id, epoch=int(epoch))


# -- pod helpers -------------------------------------------------------------


def peer_host_dirs(checkpoint_dir, host: int, n_hosts: int) -> List[str]:
    """Sibling host checkpoint dirs under the pod layout convention
    `<root>/host_<k>`: the one naming contract the CLI, the chaos driver,
    and restore reconciliation all share. Loud on a mismatch — a pod run
    whose dirs don't follow the convention would silently reconcile
    against nothing."""
    checkpoint_dir = Path(checkpoint_dir)
    if checkpoint_dir.name != f"host_{host}":
        raise ValueError(
            f"pod checkpoint dir {checkpoint_dir} must be named "
            f"host_{host} (the <root>/host_<k> pod layout, "
            "docs/RESILIENCE.md)"
        )
    return [
        str(checkpoint_dir.parent / f"host_{k}")
        for k in range(n_hosts)
        if k != host
    ]


def read_pod_commit(coord_root) -> Optional[dict]:
    """Newest pod commit marker under the coordination root (None when no
    round ever completed) — the chaos driver's one-file answer to 'did
    the gang commit a common step, and which'."""
    markers = []
    for p in Path(coord_root).glob("pod_commit_*.json"):
        try:
            with open(p) as fh:
                markers.append(json.load(fh))
        except (OSError, json.JSONDecodeError):
            continue
    if not markers:
        return None
    return max(markers, key=lambda m: m.get("step", -1))


def pod_preemption_save(
    coordinator: PodCoordinator,
    checkpoint_dir,
    state: Any,
    step: int,
    *,
    deadline_s: float = 30.0,
    round_id: str = "preempt-g0",
    metrics_writer=None,
) -> dict:
    """THE pod-mode SIGTERM checkpoint hook body (train/cli.py plugs this
    into tracing/flight.set_checkpoint_hook instead of the single-host
    preemption_save): propose this host's current step, let the barrier
    commit the gang min, and land exactly that step — by grace-saving the
    live state when this host IS the min, or by verifying the committed
    step is still retained when this host ran past it (per-step
    checkpointing + retention bound that window; a miss aborts the round
    loudly). Returns the dict the flight recorder merges into the
    stamped "preemption-checkpoint" recovery record."""
    step = int(step)

    def save_fn(commit: int) -> str:
        if commit >= step:
            # This host IS the min (commit == step by construction: the
            # min can never exceed our own proposal): grace-save the live
            # state through the throwaway sync manager.
            from glom_tpu.utils.checkpoint import preemption_save

            preemption_save(
                checkpoint_dir, state, commit, metrics_writer=metrics_writer
            )
            return "grace-saved"
        # Past the committed step: the round is satisfiable only if the
        # committed step is on disk and verifies. "On disk" is a MOVING
        # target at SIGTERM time — the loop's ASYNC save of that very
        # step may still be in flight, and its commit thread is NOT
        # paused by the signal handler (only the main thread is), so the
        # step can land while we watch. Poll for a bounded slice of the
        # grace budget before declaring the round unsatisfiable.
        from glom_tpu.utils.checkpoint import step_valid_in_dir

        wait_until = time.monotonic() + max(1.0, deadline_s * 0.25)
        while not step_valid_in_dir(checkpoint_dir, commit):
            if time.monotonic() >= wait_until:
                raise RuntimeError(
                    f"host {coordinator.host} is at step {step}, past the "
                    f"committed step {commit}, and does not retain it — "
                    "the pod round cannot complete (raise "
                    "--checkpoint-keep or lower --checkpoint-every)"
                )
            time.sleep(0.1)
        return "already-committed"

    commit = coordinator.preemption_barrier(
        round_id, step, save_fn, deadline_s=deadline_s
    )
    return {
        "step": commit,
        "pod": True,
        "round": round_id,
        "n_hosts": coordinator.n_hosts,
        "proposed_step": step,
    }
