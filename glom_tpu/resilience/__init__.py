"""Fault injection + recovery runtime (docs/RESILIENCE.md).

The observability stack (telemetry/, tracing/) sees failures; this
package makes the framework SURVIVE them — and proves it, by injecting
the failures deterministically and reconciling the stamped recovery
events against the stamped faults:

  * faults   — seedable, scoped, stamped injectors (FaultPlan + one
               injector per fault class in the catalog);
  * retry    — watchdog-aware retry-with-backoff (flapping retries,
               down fails fast);
  * ladder   — the serving degradation ladder (normal -> capped iters ->
               capped buckets -> shed; every rung reversible + stamped);
  * chaos    — end-to-end scenarios (`python -m glom_tpu.resilience`):
               kill a real training worker, require resume;
  * coordinator — pod-coordinated preemption (two-phase save barrier,
               gang supervision, cross-host restore reconciliation via
               utils/checkpoint's pod mode).

The training-side restart loop lives with the trainers
(glom_tpu/train/supervise.fit_supervised); the checkpoint integrity layer
with the checkpoints (glom_tpu/utils/checkpoint.py).
"""

from glom_tpu.resilience.coordinator import (
    BarrierAbort,
    DirectoryTransport,
    GangRestart,
    PodCoordinator,
    peer_host_dirs,
    pod_preemption_save,
    read_pod_commit,
)
from glom_tpu.resilience.faults import (
    FaultPlan,
    InjectedFault,
    barrier_delay,
    dispatch_fault,
    emit_fault,
    emit_recovery,
    message_loss,
    nan_storm,
    probe_flap,
    queue_stall,
    truncate_newest_checkpoint,
)
from glom_tpu.resilience.ladder import (
    BUCKET_CAP,
    CAPPED_ITERS,
    NORMAL,
    RUNGS,
    SHED,
    DegradationLadder,
)
from glom_tpu.resilience.retry import RetryPolicy

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "BarrierAbort",
    "DirectoryTransport",
    "GangRestart",
    "PodCoordinator",
    "peer_host_dirs",
    "pod_preemption_save",
    "read_pod_commit",
    "barrier_delay",
    "dispatch_fault",
    "emit_fault",
    "emit_recovery",
    "message_loss",
    "nan_storm",
    "probe_flap",
    "queue_stall",
    "truncate_newest_checkpoint",
    "DegradationLadder",
    "RUNGS",
    "NORMAL",
    "CAPPED_ITERS",
    "BUCKET_CAP",
    "SHED",
    "RetryPolicy",
]
