"""End-to-end chaos scenarios: kill a real training worker, require the
stack to come back.

`python -m glom_tpu.resilience --scenario kill-train --dir /tmp/chaos`
drives the full kill-and-resume loop the unit tests can only approximate:

  1. launch the REAL training CLI (train/cli.py) as a subprocess with
     per-step checkpointing, a metrics file, and a flight recorder;
  2. wait until >= --kill-after checkpoints are manifest-committed, then
     deliver the fault — SIGKILL (kill-train: the uncatchable death) or
     SIGTERM (preempt-train: the pod-preemption grace path, which must
     land a deadline-bounded checkpoint + flight dump on the way out);
  3. relaunch the same command; --resume must restore from the latest
     VALID checkpoint and run to completion;
  4. validate the evidence trail: every record schema-lints, a stamped
     "recovery" resume event exists, the train_step sequence is
     CONTINUOUS across the kill (no lost or skipped steps), and — for
     preempt-train — the SIGTERM flight dump carries the
     "preemption-checkpoint" recovery event.

Every decision the driver takes is itself a stamped record on stdout
(kind "fault" for the kill, "note"/"summary" around it), so a chaos run's
log lints like any other artifact of record. Exit 0 = the system
recovered and the evidence proves it.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import List, Optional

from glom_tpu.telemetry import schema


def _emit(rec: dict, kind: str) -> dict:
    stamped = schema.stamp(rec, kind=kind)
    print(json.dumps(stamped), flush=True)
    return stamped


def _note(text: str, **extra) -> None:
    _emit({"note": text, **extra}, kind="note")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m glom_tpu.resilience",
        description="Chaos scenarios: fault-inject a real run, verify recovery "
        "(docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--scenario",
        choices=[
            "kill-train", "preempt-train", "preempt-pod",
            "kill-serve", "rejoin-serve", "ramp-serve",
        ],
        default="kill-train",
        help="kill-train = SIGKILL mid-run (uncatchable; resume must come "
        "from the last committed checkpoint); preempt-train = SIGTERM (the "
        "grace path: deadline-bounded checkpoint + flight dump, then "
        "resume); preempt-pod = SIGTERM a strict subset of an N-process "
        "pod, then all of it — every host must commit ONE common step "
        "through the two-phase save barrier inside the grace deadline "
        "(or abort loudly, both stamped), and the relaunched gang must "
        "resume from that step with a continuous per-host train_step "
        "sequence; kill-serve = permanently fail one engine of a "
        "multi-engine serve run (seeded dispatch_fault) and require its "
        "queued tickets to re-dispatch to a sibling with a reconciling "
        "evidence trail; rejoin-serve = kill engine 0 for a BOUNDED fault "
        "window, then require probation to re-admit it (stamped "
        "engine_rejoin) and the run to finish with engine 0 alive and "
        "serving again; ramp-serve = drive a traffic ramp (low -> spike "
        "-> low) through the ELASTIC micro-server and require the "
        "autoscaler to scale OUT under the spike and back IN after it, "
        "with zero failed tickets, exact request conservation across "
        "both transitions, p99 recovered after the scale-out, and the "
        "full decision->spawn->admission-open and decision->drain->"
        "device-release chains present in the JSONL evidence alone",
    )
    p.add_argument("--dir", required=True, help="scenario working directory")
    p.add_argument("--preset", default="mnist")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument(
        "--kill-after", type=int, default=2, metavar="N",
        help="deliver the fault once N checkpoints are manifest-committed",
    )
    p.add_argument(
        "--timeout", type=float, default=600.0,
        help="per-phase deadline in seconds (a hang is a FAILURE: the whole "
        "point is that nothing in the stack may hang)",
    )
    p.add_argument(
        "--requests", type=int, default=12, metavar="N",
        help="kill-serve: synthetic requests to serve across the kill",
    )
    p.add_argument(
        "--engines", type=int, default=2, metavar="N",
        help="kill-serve: engine replicas behind the shared batcher "
        "(engine 0 is the one killed; >= 2 so a sibling exists)",
    )
    p.add_argument(
        "--ramp", default="4x100,56x0,12x250", metavar="N1xG1,...",
        help="ramp-serve: the offered-load profile (requests x gap_ms "
        "per phase; phase 1 is the spike that must force scale-out)",
    )
    p.add_argument(
        "--hosts", type=int, default=2, metavar="N",
        help="preempt-pod: real train subprocesses in the gang (>= 2; "
        "host 0 is the strict subset SIGTERM'd first)",
    )
    p.add_argument(
        "--kill-gap", type=float, default=0.5, metavar="SECONDS",
        help="preempt-pod: delay between the subset SIGTERM and the rest "
        "(the window where early-signaled hosts wait in the barrier "
        "while the others still train)",
    )
    p.add_argument(
        "--preempt-deadline", type=float, default=30.0, metavar="SECONDS",
        help="preempt-pod: the workers' SIGTERM grace budget (the barrier "
        "round must complete — or abort — inside it)",
    )
    return p


def _worker_cmd(args, paths) -> List[str]:
    return [
        sys.executable, "-u", "-m", "glom_tpu.train.cli",
        "--preset", args.preset,
        "--steps", str(args.steps),
        "--batch-size", str(args.batch_size),
        "--data", "gaussian",
        "--log-every", "1",
        "--checkpoint-dir", str(paths["ckpt"]),
        "--checkpoint-every", "1",
        "--resume",
        "--metrics-file", str(paths["metrics"]),
        "--flight-recorder", str(paths["flight"]),
    ]


def _spawn(cmd, log_path: Path) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    with open(log_path, "a") as log:
        # The child inherits a duplicate of the fd at Popen time; closing
        # the parent's handle immediately neither truncates nor races it.
        return subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env,
        )


def _manifest_count(ckpt_dir: Path) -> int:
    return len(list(ckpt_dir.glob("manifest_*.json")))


def _wait_for_checkpoints(
    proc: subprocess.Popen, ckpt_dir: Path, n: int, deadline: float
) -> bool:
    while time.monotonic() < deadline:
        if _manifest_count(ckpt_dir) >= n:
            return True
        if proc.poll() is not None:
            return False
        time.sleep(0.2)
    return False


def _records(path: Path) -> List[dict]:
    if not path.is_file():
        return []
    with open(path) as fh:
        return [rec for _, rec in schema.iter_json_lines(fh)]


def _lint(paths: List[Path]) -> List[str]:
    errors = []
    for p in paths:
        with open(p) as fh:
            errors.extend(f"{p}: {e}" for e in schema.lint_stream(fh))
    return errors


def run_kill_serve(args) -> int:
    """The serve-side kill: engine 0 of a multi-engine micro-server run is
    permanently failed via the seeded dispatch_fault seam (the in-process
    analog of a dead replica — a real SIGKILL would take every engine in
    the process with it), and the evidence trail must prove the hand-off:

      * the run COMPLETES with rc 0 — every request served by a sibling;
      * the injected faults are stamped ("fault" events at the
        engine0-dispatch site), so recovery reconciles against ground
        truth, not luck;
      * engine_failover events re-queued the dead engine's batches and an
        engine_dead event marks it; the summary shows engine0 with zero
        completed dispatches and the siblings carrying the load;
      * ticket conservation holds across the re-dispatch: n_served ==
        n_submitted, n_failed == 0 — no ticket lost, none double-served.
    """
    workdir = Path(args.dir)
    workdir.mkdir(parents=True, exist_ok=True)
    paths = {
        "metrics": workdir / "serve_metrics.jsonl",
        "log": workdir / "serve_run.log",
    }
    if args.engines < 2:
        _emit(
            {"error": "no-sibling-engine", "value": None,
             "note": f"--engines {args.engines}: kill-serve needs a "
             "sibling for the dead engine's tickets to land on"},
            kind="error",
        )
        return 1
    paths["metrics"].unlink(missing_ok=True)
    rejoin = args.scenario == "rejoin-serve"
    cmd = [
        sys.executable, "-u", "-m", "glom_tpu.serve",
        "--preset", args.preset,
        "--synthetic", str(args.requests),
        "--engines", str(args.engines),
        "--dispatch-retries", "0",
        "--iters", "auto",
        "--buckets", "1,2,4",
        "--max-batch", "4",
        "--out", str(paths["metrics"]),
    ]
    if rejoin:
        # BOUNDED fault window: engine0's first 2 dispatch attempts fail
        # (exactly the batcher's default death threshold), every attempt
        # after recovers — so probation's health dispatches succeed and
        # the fast 2-probe rejoin lands early in the run. The request gap
        # paces traffic NEAR the per-dispatch service time: the live
        # sibling is busy when the next request arrives, so the revived
        # engine (the idle waiter) must pick up work — the scenario
        # stays deterministic instead of racing worker wakeup order.
        cmd += [
            "--kill-engine", "0:after=0,until=2",
            "--rejoin", "2",
            "--rejoin-interval-ms", "50",
            "--request-gap-ms", "20",
        ]
    else:
        cmd += ["--kill-engine", "0:after=0"]
    _note(f"chaos {args.scenario}: launching micro-server",
          cmd=" ".join(cmd), workdir=str(workdir))
    _emit(
        {"fault": "engine-dead", "site": "engine0-dispatch",
         "scenario": args.scenario, "engines": args.engines,
         "fault_window": [0, 2] if rejoin else [0, None]},
        kind="fault",
    )
    proc = _spawn(cmd, paths["log"])
    try:
        rc = proc.wait(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30.0)
        _emit(
            {"error": "serve-hung", "value": None,
             "note": f"serve worker exceeded {args.timeout}s — a hang IS "
             "the failure mode this harness exists to catch"},
            kind="error",
        )
        return 1
    failures: List[str] = []
    if rc != 0:
        failures.append(
            f"serve worker rc={rc} (a dead engine must not fail the run "
            f"while siblings live); see {paths['log']}"
        )
    recs = _records(paths["metrics"])
    responses = [r for r in recs if r.get("event") == "response"]
    ok = [r for r in responses if r.get("ok")]
    if len(ok) != args.requests:
        failures.append(
            f"{len(ok)}/{args.requests} requests served ok "
            f"({len(responses)} responses)"
        )
    faults = [
        r for r in recs
        if r.get("kind") == "fault" and r.get("site") == "engine0-dispatch"
    ]
    if not faults:
        failures.append("no stamped fault events at engine0-dispatch — "
                        "the injection itself left no ground truth")
    failovers = [r for r in recs if r.get("event") == "engine_failover"]
    dead = [r for r in recs if r.get("event") == "engine_dead"]
    rejoins = [r for r in recs if r.get("event") == "engine_rejoin"]
    if not failovers:
        failures.append("no engine_failover event: the dead engine's "
                        "batches were never handed to a sibling")
    if not any(r.get("engine") == "engine0" for r in dead):
        failures.append("engine0 was never marked dead")
    summaries = [r for r in recs if r.get("event") == "summary"]
    if not summaries:
        failures.append("no serve summary record")
    else:
        s = summaries[-1]
        if s.get("n_served") != args.requests or s.get("n_failed"):
            failures.append(
                "ticket conservation broken across re-dispatch: "
                f"n_served={s.get('n_served')} n_failed={s.get('n_failed')} "
                f"n_submitted={s.get('n_submitted')} "
                f"(want n_served == {args.requests}, n_failed == 0)"
            )
        eng0 = (s.get("engines") or {}).get("engine0", {})
        if rejoin:
            # The rejoin contract: probation re-admitted engine0 AND it
            # served again — recovery proven by the evidence, not luck.
            if not any(r.get("engine") == "engine0" for r in rejoins):
                failures.append(
                    "no stamped engine_rejoin event for engine0: "
                    "probation never re-admitted the recovered engine"
                )
            if not eng0.get("alive") or not eng0.get("rejoins"):
                failures.append(
                    f"engine0 state does not reconcile with a rejoin: {eng0}"
                )
            if not eng0.get("dispatches"):
                failures.append(
                    "engine0 completed no dispatches after rejoin — it "
                    f"was re-admitted but never re-served: {eng0}"
                )
        elif eng0.get("alive") or eng0.get("dispatches"):
            failures.append(
                f"engine0 state does not reconcile with the kill: {eng0}"
            )
    # TRACE-TREE checks (schema v6, telemetry/tracectx.py): the evidence
    # is no longer a bag of events — every served request must
    # reconstruct as ONE causal tree whose per-hop executed iters and
    # wall spans conserve exactly against its resolve leaf, and the
    # failover hand-off must be VISIBLE inside at least one tree (the
    # injected dead engine's requests rode failover -> sibling dispatch).
    from glom_tpu.telemetry import tracectx

    traces = tracectx.list_traces(recs)
    resolved_traces = [
        t for t, info in sorted(traces.items()) if info["resolved"]
    ]
    if not resolved_traces:
        failures.append(
            "no resolved trace trees in the evidence: the v6 trace "
            "context never made it through the serve stack"
        )
    bad_conservation = []
    for t in resolved_traces:
        check = tracectx.conservation(recs, t)
        if not check["ok"]:
            bad_conservation.append(f"{t}: {check.get('why', '?')}")
    if bad_conservation:
        failures.append(
            "trace conservation broken (a hop's evidence is missing or "
            "double-counted): " + "; ".join(bad_conservation[:3])
        )
    crossed_failover = [
        t for t in resolved_traces
        if any(
            r.get("event") == "engine_failover"
            for r in tracectx.records_for(recs, t)
        )
    ]
    if failovers and not crossed_failover:
        failures.append(
            "no resolved trace tree contains the engine_failover hop — "
            "the hand-off happened but cannot be joined to any request"
        )
    failures.extend(_lint([paths["metrics"]]))
    summary = {
        "event": "chaos-summary",
        "scenario": args.scenario,
        "ok": not failures,
        "requests": args.requests,
        "n_fault_events": len(faults),
        "n_failovers": len(failovers),
        "n_rejoins": len(rejoins),
        "n_traces_resolved": len(resolved_traces),
        "n_traces_crossing_failover": len(crossed_failover),
        "failures": failures[:10],
    }
    _emit(summary, kind="summary")
    if failures:
        for f in failures:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    return 0


def run_ramp_serve(args) -> int:
    """The elastic-serving chaos: a real micro-server run under a
    traffic RAMP (low -> spike -> low) with the autoscaler on, proven
    from the JSONL evidence alone (docs/RESILIENCE.md):

      * the spike forces at least one SCALE-OUT and the post-spike calm
        at least one SCALE-IN (the `elastic` summary nest + timeline);
      * ZERO failed tickets and EXACT conservation across both
        transitions: every submitted request resolves (or sheds with a
        stamped reason — none at this profile), n_served + n_shed +
        n_failed == n_requests with n_failed == 0;
      * the spawned engine received NO admitted work before its warmup
        precompile completed: every warmup record of the spawned engine
        precedes its admission_open, and no dispatch on it precedes
        admission_open;
      * the decision chains are COMPLETE and ordered, joined by
        decision_id: scale_out_decision -> scale_out -> admission_open,
        and scale_in_decision -> drain_begin -> drain_flush ->
        drain_migrate -> drain_release (the engine_release record is the
        device-release leaf);
      * p99 RECOVERED after the scale-out: the tail phase's p99 sits
        strictly below the spike phase's (per-request latencies keyed by
        request id — the ramp phases are id ranges);
      * every resolved trace tree still conserves exactly (the v6
        contract holds across elastic transitions), and the stream
        schema-lints clean.
    """
    workdir = Path(args.dir)
    workdir.mkdir(parents=True, exist_ok=True)
    paths = {
        "metrics": workdir / "serve_metrics.jsonl",
        "log": workdir / "serve_run.log",
    }
    paths["metrics"].unlink(missing_ok=True)
    phases = []
    for part in args.ramp.split(","):
        n_s, _, gap_s = part.partition("x")
        phases.append((int(n_s), float(gap_s)))
    total = sum(n for n, _ in phases)
    cmd = [
        sys.executable, "-u", "-m", "glom_tpu.serve",
        "--preset", args.preset,
        "--ramp", args.ramp,
        "--elastic",
        "--min-engines", "1",
        "--max-engines", "2",
        "--elastic-low-water", "0.5",
        "--elastic-high-water", "0.8",
        "--elastic-dwell", "0.15",
        "--elastic-cooldown", "0.5",
        "--elastic-interval", "0.05",
        "--elastic-window", "2.0",
        "--elastic-p99-ms", "150",
        "--elastic-settle", "30",
        "--iters", "auto",
        "--buckets", "1,2,4",
        "--max-batch", "4",
        "--out", str(paths["metrics"]),
    ]
    _note("chaos ramp-serve: launching elastic micro-server",
          cmd=" ".join(cmd), workdir=str(workdir), total_requests=total)
    proc = _spawn(cmd, paths["log"])
    try:
        rc = proc.wait(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30.0)
        _emit(
            {"error": "serve-hung", "value": None,
             "note": f"elastic serve worker exceeded {args.timeout}s — a "
             "hang IS the failure mode this harness exists to catch"},
            kind="error",
        )
        return 1
    failures: List[str] = []
    if rc != 0:
        failures.append(
            f"serve worker rc={rc} (an elastic ramp must serve every "
            f"ticket); see {paths['log']}"
        )
    recs = _records(paths["metrics"])

    def stream_pos(pred) -> List[int]:
        return [i for i, r in enumerate(recs) if pred(r)]

    # -- fleet transitions happened at all ---------------------------------
    outs = [r for r in recs if r.get("event") == "scale_out"]
    ins = [r for r in recs if r.get("event") == "drain_release"]
    if not outs:
        failures.append("no scale_out event: the spike never grew the fleet")
    if not ins:
        failures.append("no drain_release event: the calm never shrank it")
    # -- zero failed tickets + exact conservation --------------------------
    summaries = [r for r in recs if r.get("event") == "summary"]
    if not summaries:
        failures.append("no serve summary record")
    else:
        s = summaries[-1]
        if s.get("n_failed"):
            failures.append(f"n_failed={s.get('n_failed')} — a ticket "
                            "FAILED across an elastic transition")
        if (
            (s.get("n_served") or 0) + (s.get("n_shed") or 0)
            + (s.get("n_failed") or 0)
        ) != s.get("n_requests"):
            failures.append(
                "request conservation broken: served+shed+failed != "
                f"requests in {s}"
            )
        if s.get("n_served") != total:
            failures.append(
                f"{s.get('n_served')}/{total} requests served (this "
                "profile must shed nothing)"
            )
        el = s.get("elastic") or {}
        if not el.get("n_scale_outs") or not el.get("n_scale_ins"):
            failures.append(f"elastic summary does not show a full "
                            f"out+in cycle: {el}")
        timeline = el.get("timeline") or []
        if el.get("n_engines_peak", 0) < 2 or el.get("n_engines", 0) != 1:
            failures.append(
                f"fleet timeline does not ramp 1 -> 2 -> 1: {timeline}"
            )
    # -- decision -> spawn -> admission chain ------------------------------
    for out in outs:
        did = out.get("decision_id")
        eng = out.get("engine")
        dec = stream_pos(
            lambda r: r.get("event") == "scale_out_decision"
            and r.get("decision_id") == did
        )
        adm = stream_pos(
            lambda r: r.get("event") == "admission_open"
            and r.get("decision_id") == did
        )
        here = stream_pos(
            lambda r: r.get("event") == "scale_out"
            and r.get("decision_id") == did
        )
        if not (dec and adm and dec[0] < here[0] < adm[0]):
            failures.append(
                f"scale-out chain for decision {did} is incomplete or "
                "out of order (want decision < scale_out < admission_open)"
            )
            continue
        if not out.get("spawn_ms"):
            failures.append(f"scale_out {did} carries no spawn_ms")
        if not (out.get("signal") or {}).get("rule"):
            failures.append(f"scale_out {did} embeds no triggering signal")
        # Admission-after-precompile: every warmup of the spawned engine
        # precedes admission_open, and no dispatch on it precedes it.
        warmups = stream_pos(
            lambda r: r.get("event") == "warmup" and r.get("engine") == eng
        )
        if not warmups:
            failures.append(f"spawned engine {eng} stamped no warmup "
                            "compiles — admission opened unwarmed")
        elif max(warmups) > adm[0]:
            failures.append(
                f"engine {eng} warmup compiles continued past "
                "admission_open — precompile did not complete first"
            )
        early = stream_pos(
            lambda r: r.get("event") == "dispatch" and r.get("engine") == eng
        )
        if early and early[0] < adm[0]:
            failures.append(
                f"engine {eng} dispatched BEFORE admission_open — work "
                "was admitted before the precompile finished"
            )
    # -- decision -> drain -> release chain --------------------------------
    drain_chain = (
        "scale_in_decision", "drain_begin", "drain_flush",
        "drain_migrate", "drain_release",
    )
    for rel in ins:
        did = rel.get("decision_id")
        pos = []
        for evname in drain_chain:
            at = stream_pos(
                lambda r, e=evname: r.get("event") == e
                and r.get("decision_id") == did
            )
            if not at:
                failures.append(
                    f"drain chain for decision {did} is missing {evname}"
                )
                break
            pos.append(at[0])
        else:
            if pos != sorted(pos):
                failures.append(
                    f"drain chain for decision {did} is out of order: "
                    f"{dict(zip(drain_chain, pos))}"
                )
            # engine_release is stamped by the engine itself right at
            # the device free, which the scaler runs BETWEEN the drain
            # machine's last event and its own drain_release — so the
            # leaf must sit strictly inside that window, not merely
            # exist somewhere (a release deferred to shutdown would
            # break the decision->drain->device-release chain).
            eng = rel.get("engine")
            released = stream_pos(
                lambda r: r.get("event") == "engine_release"
                and r.get("engine") == eng
            )
            if not released:
                failures.append(
                    f"drained engine {eng} never stamped "
                    "engine_release (devices not freed)"
                )
            elif not any(pos[-2] < p < pos[-1] for p in released):
                failures.append(
                    f"engine_release for {eng} at stream position(s) "
                    f"{released} sits outside the drain_migrate.."
                    f"drain_release window ({pos[-2]}, {pos[-1]}) — "
                    "devices were not freed as part of the drain chain"
                )
    # -- p99 recovered after scale-out -------------------------------------
    lat = {
        r.get("id"): r.get("latency_ms")
        for r in recs
        if r.get("event") == "response" and r.get("ok")
        and isinstance(r.get("latency_ms"), (int, float))
    }
    spike_lo = phases[0][0]
    spike_hi = spike_lo + phases[1][0]
    spike = sorted(v for k, v in lat.items() if spike_lo <= k < spike_hi)
    # Recovery is judged on the tail's STEADY-STATE half: the first tail
    # requests are submitted while the spike backlog still drains, so
    # their latency is the spike's shadow, not the scaled fleet's.
    tail_ids = sorted(k for k in lat if k >= spike_hi)
    tail_ids = tail_ids[len(tail_ids) // 2:]
    tail = sorted(lat[k] for k in tail_ids)
    if spike and tail:
        q = lambda xs, f: xs[min(len(xs) - 1, int(f * len(xs)))]
        p99_spike, p99_tail = q(spike, 0.99), q(tail, 0.99)
        if p99_tail >= p99_spike:
            failures.append(
                f"p99 did not recover after scale-out: spike {p99_spike} "
                f"ms vs tail {p99_tail} ms"
            )
    else:
        failures.append("missing spike/tail latency evidence for the "
                        "p99-recovery check")
        p99_spike = p99_tail = None
    # Breach evidence: the scaler's in-process monitor stamped at least
    # one upper-bound breach (the spike was SEEN, not just survived)...
    breaches = [r for r in recs if r.get("kind") == "slo_breach"]
    if outs and not breaches and not any(
        (o.get("signal") or {}).get("rule") == "headroom" for o in outs
    ):
        failures.append("no slo_breach records and no headroom-signal "
                        "decision — what triggered the scale-out?")
    # -- trace conservation across the transitions -------------------------
    from glom_tpu.telemetry import tracectx

    traces = tracectx.list_traces(recs)
    resolved_traces = [
        t for t, info in sorted(traces.items()) if info["resolved"]
    ]
    if len(resolved_traces) != total:
        failures.append(
            f"{len(resolved_traces)}/{total} resolved trace trees"
        )
    bad = []
    for t in resolved_traces:
        check = tracectx.conservation(recs, t)
        if not check["ok"]:
            bad.append(f"{t}: {check.get('why', '?')}")
    if bad:
        failures.append(
            "trace conservation broken across the elastic transitions: "
            + "; ".join(bad[:3])
        )
    failures.extend(_lint([paths["metrics"]]))
    summary = {
        "event": "chaos-summary",
        "scenario": args.scenario,
        "ok": not failures,
        "requests": total,
        "n_scale_outs": len(outs),
        "n_scale_ins": len(ins),
        "n_breaches": len(breaches),
        "p99_spike_ms": p99_spike,
        "p99_tail_ms": p99_tail,
        "n_traces_resolved": len(resolved_traces),
        "failures": failures[:10],
    }
    _emit(summary, kind="summary")
    if failures:
        for f in failures:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    return 0


def _pod_worker_cmd(args, workdir: Path, host: int) -> List[str]:
    return [
        sys.executable, "-u", "-m", "glom_tpu.train.cli",
        "--preset", args.preset,
        "--steps", str(args.steps),
        "--batch-size", str(args.batch_size),
        "--data", "gaussian",
        "--log-every", "1",
        "--checkpoint-dir", str(workdir / "ckpt" / f"host_{host}"),
        "--checkpoint-every", "1",
        "--checkpoint-keep", "50",
        "--resume",
        "--pod-index", str(host),
        "--pod-count", str(args.hosts),
        "--pod-dir", str(workdir / "coord"),
        "--preempt-deadline", str(args.preempt_deadline),
        "--metrics-file", str(workdir / f"metrics_h{host}.jsonl"),
        "--flight-recorder", str(workdir / f"flight_h{host}"),
    ]


def run_preempt_pod(args) -> int:
    """The pod-preemption acceptance: N REAL train subprocesses under the
    coordinated save barrier. SIGTERM a STRICT SUBSET first (those hosts
    propose and wait inside the barrier while the rest keep training),
    then the rest — the round must commit ONE common step on every host
    inside the grace deadline (or abort loudly; both outcomes stamped).
    Relaunch the gang: every host must resume from exactly that step and
    the per-host train_step sequences must be continuous — all proven
    from the JSONL evidence alone."""
    if args.hosts < 2:
        _emit(
            {"error": "no-pod", "value": None,
             "note": f"--hosts {args.hosts}: preempt-pod needs >= 2 "
             "processes (one host is preempt-train)"},
            kind="error",
        )
        return 1
    workdir = Path(args.dir)
    workdir.mkdir(parents=True, exist_ok=True)
    hosts = list(range(args.hosts))
    ckpt_dirs = {h: workdir / "ckpt" / f"host_{h}" for h in hosts}
    metrics = {h: workdir / f"metrics_h{h}.jsonl" for h in hosts}
    flights = {h: workdir / f"flight_h{h}" for h in hosts}
    cmds = {h: _pod_worker_cmd(args, workdir, h) for h in hosts}
    _note(
        f"chaos preempt-pod: launching {args.hosts}-host gang",
        cmd=" ".join(cmds[0]), workdir=str(workdir),
    )

    # Phase 1: run until every host committed >= kill-after manifests,
    # SIGTERM the strict subset (host 0), then — inside the grace window,
    # while the subset waits in the barrier — the rest.
    procs = {h: _spawn(cmds[h], workdir / f"run1_h{h}.log") for h in hosts}
    deadline = time.monotonic() + args.timeout
    try:
        for h in hosts:
            if not _wait_for_checkpoints(
                procs[h], ckpt_dirs[h], args.kill_after, deadline
            ):
                _emit(
                    {"error": "worker-never-checkpointed", "value": None,
                     "note": f"host {h}: no {args.kill_after} committed "
                     f"checkpoints within {args.timeout}s "
                     f"(rc={procs[h].poll()}); see run1_h{h}.log"},
                    kind="error",
                )
                return 1
        if any(procs[h].poll() is not None for h in hosts):
            _emit(
                {"error": "kill-window-missed", "value": None,
                 "note": "a host exited before the fault landed; lower "
                 f"--kill-after (now {args.kill_after}) or raise --steps "
                 f"(now {args.steps})"},
                kind="error",
            )
            return 1
        subset = hosts[:1]  # the STRICT subset: host 0 alone
        for h in subset:
            os.kill(procs[h].pid, signal.SIGTERM)
            _emit(
                {"fault": "sigterm", "site": "pod-worker",
                 "host": h, "pid": procs[h].pid, "wave": "subset",
                 "manifests_at_kill": _manifest_count(ckpt_dirs[h]),
                 "wall_time_s": round(time.time(), 3)},
                kind="fault",
            )
        time.sleep(args.kill_gap)
        for h in hosts:
            if h in subset:
                continue
            os.kill(procs[h].pid, signal.SIGTERM)
            _emit(
                {"fault": "sigterm", "site": "pod-worker",
                 "host": h, "pid": procs[h].pid, "wave": "all",
                 "manifests_at_kill": _manifest_count(ckpt_dirs[h]),
                 "wall_time_s": round(time.time(), 3)},
                kind="fault",
            )
        rcs = {}
        for h in hosts:
            try:
                rcs[h] = procs[h].wait(timeout=min(120.0, args.timeout))
            except subprocess.TimeoutExpired:
                _emit(
                    {"error": "worker-outlived-kill", "value": None,
                     "note": f"host {h} pid {procs[h].pid} still alive "
                     "after SIGTERM + grace; hard-killing"},
                    kind="error",
                )
                return 1
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30.0)
    if any(rc == 0 for rc in rcs.values()):
        _emit(
            {"error": "kill-window-missed", "value": None,
             "note": f"a host exited 0 despite SIGTERM (rcs={rcs}); "
             "lower --kill-after or raise --steps"},
            kind="error",
        )
        return 1
    _note(f"phase 1 done: gang SIGTERM'd (rcs={rcs})")

    # The barrier's verdict: the pod commit marker is written by host 0
    # only when EVERY host acked the committed step.
    from glom_tpu.resilience.coordinator import read_pod_commit

    commit = read_pod_commit(workdir / "coord")
    if commit is None:
        _emit(
            {"error": "no-pod-commit", "value": None,
             "note": "no pod_commit_<step>.json under the coordination "
             "dir: the barrier never completed (an abort should be "
             "stamped in the flight dumps — this smoke injects no "
             "faults, so a commit was required)"},
            kind="error",
        )
        return 1
    common = int(commit["step"])
    _note(f"barrier committed common step {common}",
          proposals=commit.get("proposals"))

    # Phase 2: relaunch the whole gang; every host must reconcile to the
    # common step and run to completion.
    procs2 = {h: _spawn(cmds[h], workdir / f"run2_h{h}.log") for h in hosts}
    for h in hosts:
        try:
            rc2 = procs2[h].wait(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            for p in procs2.values():
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30.0)
            _emit(
                {"error": "resume-hung", "value": None,
                 "note": f"relaunched host {h} exceeded {args.timeout}s"},
                kind="error",
            )
            return 1
        if rc2 != 0:
            _emit(
                {"error": "resume-failed", "value": None,
                 "note": f"relaunched host {h} rc={rc2}; see run2_h{h}.log"},
                kind="error",
            )
            return 1
    _note("phase 2 done: gang resumed and ran to completion")

    # Phase 3: the evidence must prove ONE common resume step and
    # per-host continuity.
    failures: List[str] = []
    dumps_all: List[Path] = []
    for h in hosts:
        recs = _records(metrics[h])
        resumes = [
            r for r in recs
            if r.get("kind") == "recovery"
            and r.get("action") == "resume-from-checkpoint"
        ]
        if not resumes:
            failures.append(f"host {h}: no stamped resume-from-checkpoint")
        else:
            got = {int(r["step"]) for r in resumes
                   if isinstance(r.get("step"), (int, float))}
            if got != {common}:
                failures.append(
                    f"host {h}: resumed from {sorted(got)}, want the "
                    f"committed common step {{{common}}}"
                )
        steps = sorted(
            {int(r["step"]) for r in recs
             if r.get("kind") == "train_step"
             and isinstance(r.get("step"), (int, float))}
        )
        want = set(range(args.steps))
        # The grace save commits PAST the last flushed record on the host
        # that WAS the min (its in-flight step's record died with the
        # process; the training is in the checkpoint) — exactly one
        # missing step, the committed step minus one, same as
        # preempt-train. Hosts past the min re-train and re-log the gap.
        missing = want - set(steps)
        if not steps or not missing <= {common - 1}:
            failures.append(
                f"host {h}: train_step sequence not continuous: got "
                f"{steps}, missing {sorted(missing)}, allowed gap "
                f"{{{common - 1}}}"
            )
        dumps = sorted(flights[h].glob("flight_*.jsonl"))
        dumps_all.extend(dumps)
        if not dumps:
            failures.append(f"host {h}: no flight dumps")
            continue
        drecs = [r for d in dumps for r in _records(d)]
        barrier = [r for r in drecs if r.get("kind") == "barrier"]
        phases = {r.get("phase") for r in barrier}
        if not {"propose", "commit", "saved", "complete"} <= phases:
            failures.append(
                f"host {h}: barrier round incomplete in the evidence "
                f"(phases {sorted(phases)})"
            )
        commits = {r.get("step") for r in barrier
                   if r.get("phase") == "commit"}
        if commits != {common}:
            failures.append(
                f"host {h}: stamped barrier commit {sorted(commits)} != "
                f"pod marker step {common}"
            )
        preempt = [
            r for r in drecs
            if r.get("kind") == "recovery"
            and r.get("action") == "preemption-checkpoint"
        ]
        if not any(r.get("ok") and r.get("pod") for r in preempt):
            failures.append(
                f"host {h}: no successful POD preemption-checkpoint "
                "recovery event in the flight dumps"
            )
    failures.extend(_lint([*metrics.values(), *dumps_all]))

    summary = {
        "event": "chaos-summary",
        "scenario": args.scenario,
        "ok": not failures,
        "hosts": args.hosts,
        "steps": args.steps,
        "committed_common_step": common,
        "proposals": commit.get("proposals"),
        "n_flight_dumps": len(dumps_all),
        "failures": failures[:10],
    }
    _emit(summary, kind="summary")
    if failures:
        for f in failures:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    return 0


def run_scenario(args) -> int:
    if args.scenario == "ramp-serve":
        return run_ramp_serve(args)
    if args.scenario in ("kill-serve", "rejoin-serve"):
        return run_kill_serve(args)
    if args.scenario == "preempt-pod":
        return run_preempt_pod(args)
    workdir = Path(args.dir)
    paths = {
        "ckpt": workdir / "ckpt",
        "flight": workdir / "flight",
        "metrics": workdir / "metrics.jsonl",
        "run1_log": workdir / "run1.log",
        "run2_log": workdir / "run2.log",
    }
    workdir.mkdir(parents=True, exist_ok=True)
    sig = signal.SIGKILL if args.scenario == "kill-train" else signal.SIGTERM
    cmd = _worker_cmd(args, paths)
    _note(
        f"chaos {args.scenario}: launching worker", cmd=" ".join(cmd),
        workdir=str(workdir),
    )

    # Phase 1: run until enough checkpoints committed, then kill.
    proc = _spawn(cmd, paths["run1_log"])
    deadline = time.monotonic() + args.timeout
    try:
        if not _wait_for_checkpoints(proc, paths["ckpt"], args.kill_after, deadline):
            _emit(
                {
                    "error": "worker-never-checkpointed",
                    "value": None,
                    "note": f"no {args.kill_after} committed checkpoints within "
                    f"{args.timeout}s (rc={proc.poll()}); see {paths['run1_log']}",
                },
                kind="error",
            )
            return 1
        if proc.poll() is not None:
            # The worker finished between polls before the fault could
            # land — the scenario exercised nothing. A distinct stamped
            # error (not "survived-kill"): rerun with a smaller
            # --kill-after or more --steps.
            _emit(
                {"error": "kill-window-missed", "value": None,
                 "note": f"worker exited rc={proc.returncode} before the "
                 f"kill landed; lower --kill-after (now {args.kill_after}) "
                 f"or raise --steps (now {args.steps})"},
                kind="error",
            )
            return 1
        os.kill(proc.pid, sig)
        _emit(
            {
                "fault": "sigkill" if sig == signal.SIGKILL else "sigterm",
                "site": "train-worker",
                "pid": proc.pid,
                "manifests_at_kill": _manifest_count(paths["ckpt"]),
                "wall_time_s": round(time.time(), 3),
            },
            kind="fault",
        )
        try:
            rc1 = proc.wait(timeout=min(120.0, args.timeout))
        except subprocess.TimeoutExpired:
            # A worker that outlives its kill signal (e.g. a wedged
            # SIGTERM grace save) is itself a finding — stamped, like
            # every other failure path here, never a raw traceback.
            _emit(
                {"error": "worker-outlived-kill", "value": None,
                 "note": f"worker pid {proc.pid} still alive "
                 f"{min(120.0, args.timeout)}s after {sig!s}; hard-killing"},
                kind="error",
            )
            return 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30.0)
    if rc1 == 0:
        # rc 0 after the signal means the worker was already past its
        # last unsaved work when the fault landed (the exit raced the
        # kill) — same remedy as a missed window, stamped distinctly
        # from a worker that IGNORED the signal (which cannot exit 0:
        # SIGKILL is uncatchable and the SIGTERM chain raises).
        _emit(
            {"error": "kill-window-missed", "value": None,
             "note": "worker exited 0 despite the injected kill (exit "
             "raced the signal); lower --kill-after or raise --steps"},
            kind="error",
        )
        return 1
    _note(f"phase 1 done: worker killed (rc={rc1})")

    # Phase 2: relaunch; --resume must restore and run to completion.
    proc2 = _spawn(cmd, paths["run2_log"])
    try:
        rc2 = proc2.wait(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        proc2.kill()
        proc2.wait(timeout=30.0)
        _emit(
            {"error": "resume-hung", "value": None,
             "note": f"phase-2 worker exceeded {args.timeout}s — a hang IS "
             "the failure mode this harness exists to catch"},
            kind="error",
        )
        return 1
    if rc2 != 0:
        _emit(
            {"error": "resume-failed", "value": None,
             "note": f"phase-2 worker rc={rc2}; see {paths['run2_log']}"},
            kind="error",
        )
        return 1
    _note("phase 2 done: resumed worker ran to completion")

    # Phase 3: the evidence trail must prove the recovery.
    failures: List[str] = []
    recs = _records(paths["metrics"])
    steps = sorted(
        {int(r["step"]) for r in recs
         if r.get("kind") == "train_step" and isinstance(r.get("step"), (int, float))}
    )
    resumes = [
        r for r in recs
        if r.get("kind") == "recovery"
        and r.get("action") == "resume-from-checkpoint"
    ]
    if not resumes:
        failures.append("no stamped resume-from-checkpoint recovery event")
    want = set(range(args.steps))
    missing = want - set(steps)
    # SIGKILL resume re-trains (and re-logs) everything after the last
    # committed step, so the stream must be gapless. The SIGTERM grace
    # save deliberately commits PAST the last flushed record (the
    # in-flight step's record dies with the process, its training is in
    # the checkpoint), so exactly that one step may be missing.
    allowed = set()
    if args.scenario == "preempt-train" and resumes:
        r0 = resumes[0].get("step")
        if isinstance(r0, (int, float)):
            allowed = {int(r0) - 1}
    if not steps or not missing <= allowed:
        failures.append(
            f"train_step sequence not continuous: got {steps}, want "
            f"{sorted(want)} (missing {sorted(missing)}, allowed gap "
            f"{sorted(allowed)})"
        )
    dumps = sorted(paths["flight"].glob("flight_*.jsonl"))
    if not dumps:
        failures.append(f"no flight dumps under {paths['flight']}")
    if args.scenario == "preempt-train":
        preempt = [
            r
            for d in dumps
            for r in _records(d)
            if r.get("kind") == "recovery"
            and r.get("action") == "preemption-checkpoint"
        ]
        if not any(r.get("ok") for r in preempt):
            failures.append(
                "no successful preemption-checkpoint recovery event in any "
                "flight dump (the SIGTERM grace path did not land a save)"
            )
    failures.extend(_lint([paths["metrics"], *dumps]))

    resumed_step: Optional[int] = (
        resumes[0].get("step") if resumes else None
    )
    summary = {
        "event": "chaos-summary",
        "scenario": args.scenario,
        "ok": not failures,
        "steps": args.steps,
        "resumed_from_step": resumed_step,
        "n_recovery_events": len([r for r in recs if r.get("kind") == "recovery"]),
        "n_flight_dumps": len(dumps),
        "failures": failures[:10],
    }
    _emit(summary, kind="summary")
    if failures:
        for f in failures:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    return run_scenario(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
