"""The serving degradation ladder: shed is the LAST resort, not the only
move.

Before this module the batcher had exactly two answers to trouble: serve
normally, or shed. The ladder inserts the rungs between them — each one a
cheaper serving mode, each transition a stamped, REVERSIBLE decision:

    rung 0  normal        — configured route (iters="auto", full buckets)
    rung 1  capped_iters  — early exit capped at a fixed degraded budget:
                            every request costs a bounded, smaller number
                            of column iterations (quality degrades
                            gracefully; GLOM consensus at half budget is a
                            coarser island structure, not garbage)
    rung 2  bucket_cap    — additionally gather smaller batches (a capped
                            dispatch ceiling drains the queue in smaller,
                            faster bites — latency per dispatch drops when
                            the backend is struggling)
    rung 3  shed          — new admissions fail fast (the old behavior,
                            now the floor of the ladder instead of its
                            entirety)

Inputs per evaluation: queue fill fraction (pressure) and the watchdog
backend state. Fill >= high_water steps DOWN one rung; fill <= low_water
steps back UP; a FLAPPING backend pins the ladder at capped_iters or
worse while the flap lasts — but flapping alone NEVER sheds (satellite
contract: flapping is a degraded-service signal, not an outage; "down"
is handled by the batcher's fast-fail shed path, not the ladder). A
min_dwell_s hysteresis keeps one burst from riding the ladder up and
down per dispatch.

Every transition is emitted via serve/events.emit_serve (kind "serve",
event "ladder") so backend_state rides along, and kept in an in-memory
timeline for end-of-run summaries — the same discipline as the watchdog's
transitions. Thread-safe: observe() runs on the batcher worker while
rung()/record() serve caller threads.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

RUNGS = ("normal", "capped_iters", "bucket_cap", "shed")
NORMAL, CAPPED_ITERS, BUCKET_CAP, SHED = range(4)


def class_rungs(shed_position: int, n_classes: int) -> tuple:
    """(degrade_rung, shed_rung) for the SLO class at `shed_position` of
    `n_classes` in the shed order (glom_tpu/serve/qos.py; 0 = first to
    shed). The ladder itself stays ONE shared pressure signal — classes
    differ in WHICH rung starts costing them:

      * the FIRST class in the shed order (the batch end) sheds a rung
        EARLY (bucket_cap instead of shed): under pressure the fleet
        drops its cheapest tenant before anything else degrades hard;
      * the LAST class (the premium end) HOLDS its full route until
        bucket_cap (one rung past everyone else's capped_iters) and
        sheds only at the ladder's own floor;
      * everything between degrades at capped_iters and sheds at shed —
        the classless semantics, unchanged.

    One class (or a classless config) degrades/sheds exactly like PR 18:
    (capped_iters, shed)."""
    if not 0 <= shed_position < n_classes:
        raise ValueError(
            f"shed_position {shed_position} outside 0..{n_classes - 1}"
        )
    if n_classes <= 1:
        return (CAPPED_ITERS, SHED)
    degrade = BUCKET_CAP if shed_position == n_classes - 1 else CAPPED_ITERS
    shed = BUCKET_CAP if shed_position == 0 else SHED
    return (degrade, shed)


class DegradationLadder:
    """Pressure/flap-driven serving mode, one reversible rung at a time."""

    def __init__(
        self,
        *,
        degraded_iters: int,
        bucket_cap: int,
        high_water: float = 0.75,
        low_water: float = 0.25,
        min_dwell_s: float = 0.25,
        writer=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 <= low_water < high_water <= 1.0:
            raise ValueError(
                f"need 0 <= low_water ({low_water}) < high_water "
                f"({high_water}) <= 1"
            )
        if degraded_iters < 1:
            raise ValueError(f"degraded_iters {degraded_iters} must be >= 1")
        if bucket_cap < 1:
            raise ValueError(f"bucket_cap {bucket_cap} must be >= 1")
        if min_dwell_s < 0:
            raise ValueError(f"min_dwell_s {min_dwell_s} must be >= 0")
        self.degraded_iters = degraded_iters
        self.bucket_cap = bucket_cap
        self.high_water = high_water
        self.low_water = low_water
        self.min_dwell_s = min_dwell_s
        self.writer = writer
        self._clock = clock
        self._lock = threading.Lock()
        self._rung = NORMAL
        self._last_change: Optional[float] = None
        self._transitions: List[dict] = []
        self._n_degrade = 0
        self._n_restore = 0

    @classmethod
    def from_config(cls, cfg, scfg, *, writer=None, **overrides):
        """Resolve the ladder knobs from a (GlomConfig, ServeConfig) pair:
        degraded_iters defaults to half the model's iteration budget
        (floor 1) and bucket_cap to half the admission ceiling — both
        overridable per ServeConfig field or kwarg."""
        kw = dict(
            degraded_iters=(
                scfg.degraded_iters
                if scfg.degraded_iters is not None
                else max(1, cfg.default_iters // 2)
            ),
            bucket_cap=(
                scfg.degraded_max_batch
                if scfg.degraded_max_batch is not None
                else max(1, scfg.max_batch // 2)
            ),
            high_water=scfg.ladder_high_water,
            low_water=scfg.ladder_low_water,
            writer=writer,
        )
        kw.update(overrides)
        return cls(**kw)

    # -- the decision ------------------------------------------------------

    def observe(self, *, queue_fill: float, backend_state: str = "up") -> int:
        """Evaluate one (pressure, backend) observation; returns the rung
        now in force. At most ONE rung of movement per call, at most one
        transition per min_dwell_s — the ladder is deliberately slower
        than the queue it watches."""
        event = None
        with self._lock:
            now = self._clock()
            desired = self._rung
            reason = None
            if queue_fill >= self.high_water and self._rung < SHED:
                desired, reason = self._rung + 1, "pressure"
            elif queue_fill <= self.low_water and self._rung > NORMAL:
                desired, reason = self._rung - 1, "drained"
            if backend_state == "flapping":
                if desired < CAPPED_ITERS:
                    # Flap floor: degraded service while the backend
                    # settles. NOT shed — a flapping backend still serves.
                    desired, reason = CAPPED_ITERS, "backend-flapping"
            dwell_ok = (
                self._last_change is None
                or now - self._last_change >= self.min_dwell_s
            )
            if desired != self._rung and dwell_ok:
                prev = self._rung
                self._rung = desired
                self._last_change = now
                if desired > prev:
                    self._n_degrade += 1
                else:
                    self._n_restore += 1
                event = {
                    "event": "ladder",
                    "rung": RUNGS[desired],
                    "prev_rung": RUNGS[prev],
                    "direction": "degrade" if desired > prev else "restore",
                    "reason": reason,
                    "queue_fill": round(queue_fill, 3),
                }
            rung = self._rung
        if event is not None:
            # Emit outside the lock (the writer chain locks on its own);
            # emit_serve merges the live backend_state onto the record.
            from glom_tpu.serve.events import emit_serve

            stamped = emit_serve(self.writer, event)
            with self._lock:
                self._transitions.append(stamped)
        return rung

    # -- reads -------------------------------------------------------------

    def rung(self) -> int:
        with self._lock:
            return self._rung

    def rung_name(self) -> str:
        return RUNGS[self.rung()]

    def timeline(self) -> List[dict]:
        """The stamped transition events, oldest first."""
        with self._lock:
            return list(self._transitions)

    def record(self) -> dict:
        """The fields a serve summary stamps."""
        with self._lock:
            return {
                "ladder_rung": RUNGS[self._rung],
                "ladder_degrades": self._n_degrade,
                "ladder_restores": self._n_restore,
            }
