"""Deterministic fault injection: seedable, scoped, stamped.

The observability stack (PRs 2-4) can SEE every failure; this harness
MAKES them, on purpose and on the record, so the recovery machinery
(docs/RESILIENCE.md) is tested against ground truth instead of luck. The
design contract, in order of importance:

  * DETERMINISTIC — every injection decision comes from a FaultPlan: a
    per-site schedule (explicit call indices, or a seeded per-site RNG
    rate inside a window). Same seed, same call sequence, same faults —
    a chaos test that flakes is worse than no chaos test.
  * STAMPED — each injection lands as a schema-v4 "fault" event (fault
    class, site, occurrence index, per-injection detail) through the
    usual writer-else-flight delivery, so a run's recovery events can be
    reconciled one-to-one against exactly what was injected.
  * SCOPED — injectors attach at the seams the real faults enter
    through: the watchdog's probe (backend flaps), the engine's dispatch
    hook (dispatch exceptions, queue stalls), the data iterator (NaN
    storms), any callable via plan.wrap (checkpoint-write failures), the
    checkpoint directory itself (torn files), and a worker process
    (SIGTERM / SIGKILL preemption, glom_tpu/resilience/chaos.py).

Nothing here runs unless wired in: production code paths carry the seams
(BackendWatchdog.set_probe_fault, InferenceEngine fault_hook), not the
faults.
"""

from __future__ import annotations

import random
import threading
import time
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from glom_tpu.telemetry import schema


class InjectedFault(RuntimeError):
    """Default exception raised by exception-type injectors — tests can
    assert THIS fault surfaced (or was recovered from), not a lookalike."""


def emit_fault(writer, rec: dict) -> dict:
    """Stamp one "fault" event and deliver writer-else-flight (the same
    routing every other sink takes). Returns the stamped record."""
    from glom_tpu.tracing.flight import write_or_observe

    stamped = schema.stamp(rec, kind="fault")
    write_or_observe(writer, stamped)
    return stamped


def emit_recovery(writer, rec: dict) -> dict:
    """The recovery twin of emit_fault: ONE stamp-and-deliver definition
    for every "recovery" emit site (the restart loop, the retry policy,
    the checkpoint torn-step skip) — the serve/events.emit_serve lesson
    applied to this kind. Returns the stamped record."""
    from glom_tpu.telemetry import tracectx
    from glom_tpu.tracing.flight import write_or_observe

    stamped = schema.stamp(rec, kind="recovery")
    # A recovery emitted from under a serve dispatch (a dispatch-retry,
    # say) inherits that dispatch's trace context, so the retry attempt
    # appears in the request's causal tree (telemetry/tracectx.py).
    if not any(k in stamped for k in ("trace_id", "trace_ids")):
        stamped.update(tracectx.current_fields())
    write_or_observe(writer, stamped)
    return stamped


class FaultPlan:
    """The one seeded decision source every injector consults.

    register() declares a site's schedule; fires() is called by the
    injector once per potential-injection point and returns whether to
    inject, stamping the "fault" event when it does. Schedules:

      * at=(i, j, ...) — fire exactly on those 0-based call indices (the
        form the pinned-window tests use);
      * rate=p with start/stop — fire each in-window call with seeded
        probability p (per-site `random.Random(f"{seed}:{site}")`, so
        adding a site never perturbs another site's schedule).

    Thread-safe: per-site counters and the event log ride one lock
    (probes fire from the watchdog thread, dispatch faults from the
    batcher worker, while the test thread reads events()/record())."""

    def __init__(self, seed: int = 0, *, writer=None, clock=time.monotonic):
        self.seed = seed
        self.writer = writer
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._sites: Dict[str, dict] = {}
        self._calls: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._events: List[dict] = []

    def register(
        self,
        site: str,
        *,
        at: Optional[Iterable[int]] = None,
        rate: Optional[float] = None,
        start: int = 0,
        stop: Optional[int] = None,
        fault: Optional[str] = None,
    ) -> "FaultPlan":
        """Declare `site`'s schedule; returns self for chaining. `fault`
        names the fault class on the stamped events (default: the site)."""
        if (at is None) == (rate is None):
            raise ValueError(
                f"site {site!r}: exactly one of at=(indices) or rate=p"
            )
        if rate is not None and not 0.0 <= rate <= 1.0:
            raise ValueError(f"site {site!r}: rate {rate} outside 0..1")
        with self._lock:
            self._sites[site] = {
                "at": frozenset(int(i) for i in at) if at is not None else None,
                "rate": rate,
                "start": start,
                "stop": stop,
                "fault": fault if fault is not None else site,
                "rng": random.Random(f"{self.seed}:{site}"),
            }
            self._calls.setdefault(site, 0)
            self._fired.setdefault(site, 0)
        return self

    def fires(self, site: str, **detail) -> bool:
        """One potential-injection point at `site`: decide, count, stamp.
        Unregistered sites never fire (an injector can be wired in
        unconditionally and armed per test)."""
        with self._lock:
            index = self._calls.get(site, 0)
            self._calls[site] = index + 1
            spec = self._sites.get(site)
            fire = False
            if spec is not None and index >= spec["start"] and (
                spec["stop"] is None or index < spec["stop"]
            ):
                if spec["at"] is not None:
                    fire = index in spec["at"]
                else:
                    fire = spec["rng"].random() < spec["rate"]
            if fire:
                self._fired[site] = self._fired.get(site, 0) + 1
                event = {
                    "fault": spec["fault"],
                    "site": site,
                    "index": index,
                    "t": round(self._clock() - self._t0, 4),
                    "wall_time_s": round(time.time(), 3),
                    **detail,
                }
        if not fire:
            return False
        # Stamp OUTSIDE the lock: the writer chain (MetricsWriter, flight
        # ring) takes its own locks and must not nest inside ours.
        stamped = emit_fault(self.writer, event)
        with self._lock:
            self._events.append(stamped)
        return True

    def wrap(
        self,
        fn: Callable,
        site: str,
        *,
        exc: Optional[Callable[[], BaseException]] = None,
        before: Optional[Callable[[], None]] = None,
    ) -> Callable:
        """Generic injector: when the plan fires at `site`, run `before`
        (a stall, a truncation) and/or raise `exc()` INSTEAD of calling
        through — the checkpoint-write-failure form:

            ckpt.save = plan.wrap(ckpt.save, "ckpt-write",
                                  exc=lambda: OSError("injected"))
        """
        if exc is None and before is None:
            exc = lambda: InjectedFault(f"injected fault at {site}")

        def wrapped(*args, **kwargs):
            if self.fires(site):
                if before is not None:
                    before()
                if exc is not None:
                    raise exc()
            return fn(*args, **kwargs)

        return wrapped

    # -- reads -------------------------------------------------------------

    def events(self) -> List[dict]:
        """The stamped "fault" events injected so far — the ground truth a
        chaos test reconciles recovery against."""
        with self._lock:
            return list(self._events)

    def record(self) -> dict:
        """Per-site calls/fired summary (a stampable rollup)."""
        with self._lock:
            return {
                "seed": self.seed,
                "sites": {
                    s: {"calls": self._calls.get(s, 0),
                        "fired": self._fired.get(s, 0)}
                    for s in sorted(self._sites)
                },
            }


# -- injectors: one per fault class in the catalog --------------------------


def probe_flap(plan: FaultPlan, site: str = "watchdog-probe"):
    """Backend-flap injector for BackendWatchdog.set_probe_fault: on
    scheduled probe calls the REAL probe result is replaced with None
    (backend looks down); off-schedule calls pass through untouched. The
    state machine then walks its genuine up/down/flapping transitions —
    nothing about the watchdog is mocked, only what it observes."""

    def fault(n: Optional[int]) -> Optional[int]:
        if plan.fires(site, probe_result=None if n is None else int(n)):
            return None
        return n

    return fault


def dispatch_fault(
    plan: FaultPlan,
    site: str = "engine-dispatch",
    *,
    exc_type: Callable[[str], BaseException] = InjectedFault,
):
    """Dispatch-exception injector for InferenceEngine(fault_hook=...):
    raises on scheduled dispatch ATTEMPTS (retries re-roll the schedule,
    so `at=(0,)` means 'first attempt fails, the retry lands')."""

    def hook(ctx: dict) -> None:
        from glom_tpu.telemetry import tracectx

        # An injection that lands under a dispatch scope stamps the
        # victim requests' trace context on the fault event, so a chaos
        # run's trace trees show WHICH requests each injection hit.
        if plan.fires(
            site,
            **{k: ctx.get(k) for k in ("bucket", "n_valid", "attempt")},
            **tracectx.current_fields(),
        ):
            raise exc_type(f"injected dispatch fault at {site}")

    return hook


def spawn_fault(
    plan: FaultPlan,
    site: str = "engine-spawn",
    *,
    exc_type: Callable[[str], BaseException] = InjectedFault,
):
    """Scale-out spawn-failure injector for the elastic autoscaler
    (serve/elastic.Autoscaler(spawn_hook=...)): raises on scheduled
    spawn ATTEMPTS before the engine factory runs — the scaler must
    ROLL BACK loudly (stamped spawn_rollback, no registration, cooldown
    still charged so a persistent fault cannot hot-spin spawns) instead
    of admitting a half-built replica. Every injection is a stamped
    "fault" event, so the ramp-serve chaos run reconciles rollbacks
    against exactly what was injected."""

    def hook(ctx: dict) -> None:
        if plan.fires(
            site,
            **{k: (ctx or {}).get(k) for k in ("attempt", "n_engines")},
        ):
            raise exc_type(f"injected spawn fault at {site}")

    return hook


def queue_stall(
    plan: FaultPlan,
    site: str = "queue-stall",
    *,
    stall_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
):
    """Queue-stall injector: a hook that SLEEPS on scheduled calls —
    attach as an engine fault_hook (dispatch slows, the bounded queue
    backs up, the degradation ladder feels real pressure) or wrap any
    callable via plan.wrap(fn, site, before=queue_stall(...))."""

    def hook(ctx: Optional[dict] = None) -> None:
        del ctx
        if plan.fires(site, stall_s=stall_s):
            sleep(stall_s)

    return hook


def nan_storm(
    data: Iterator,
    plan: FaultPlan,
    site: str = "nan-storm",
    *,
    fraction: float = 1.0,
) -> Iterator:
    """NaN-grad-storm injector: wraps a batch iterator; scheduled batches
    are copied and poisoned with NaN over the leading `fraction` of
    elements — the in-graph NaN/Inf guard (telemetry/diagnostics.py) and
    the fit loop's anomaly events are the recovery machinery under test."""
    if not 0.0 < fraction <= 1.0:
        raise ValueError(f"fraction {fraction} outside (0, 1]")
    for i, batch in enumerate(data):
        if plan.fires(site, batch=i):
            poisoned = np.array(batch, dtype=np.float32, copy=True)
            flat = poisoned.reshape(-1)
            flat[: max(1, int(fraction * flat.size))] = np.nan
            yield poisoned
        else:
            yield batch


def message_loss(plan: FaultPlan, site: str = "barrier-msg"):
    """Barrier-message-loss injector for the pod coordinator's transport
    (DirectoryTransport(fault_hook=...)): on scheduled posts the message
    is silently DROPPED — the sender is not told, exactly like a lossy
    link — and the waiting peers must abort the round loudly at the
    deadline (coordinator.BarrierAbort, stamped). ctx carries the
    round/phase/host so the stamped fault reconciles one-to-one against
    the abort it caused."""

    def hook(ctx: dict) -> bool:
        return plan.fires(
            site,
            **{k: ctx.get(k) for k in ("round", "phase", "host")},
        )

    return hook


def barrier_delay(
    plan: FaultPlan,
    site: str = "barrier-delay",
    *,
    delay_s: float = 0.5,
    sleep: Callable[[float], None] = time.sleep,
):
    """Deadline-overrun injector for the same transport seam: scheduled
    posts are STALLED by `delay_s` before the message lands (the message
    is not lost — it is late). Sized past the round's grace deadline
    this forces the waiting peers into the loud abort path; sized under
    it, it proves slow-but-alive hosts still commit."""

    def hook(ctx: dict) -> bool:
        if plan.fires(
            site,
            delay_s=delay_s,
            **{k: ctx.get(k) for k in ("round", "phase", "host")},
        ):
            sleep(delay_s)
        return False  # never dropped — only delayed

    return hook


def truncate_newest_checkpoint(
    directory, *, writer=None
) -> Optional[Tuple[int, str]]:
    """Torn-checkpoint injector: truncate the largest file of the NEWEST
    step under an Orbax checkpoint directory to half its size, stamping
    the "fault" event. Returns (step, path) or None when no step exists.
    The recovery under test: latest_step()/restore() must skip the torn
    step and land on the previous valid one (utils/checkpoint.py)."""
    directory = Path(directory)
    steps = sorted(
        (int(p.name), p)
        for p in directory.iterdir()
        if p.is_dir() and p.name.isdigit()
    )
    if not steps:
        return None
    step, step_dir = steps[-1]
    files = [p for p in step_dir.rglob("*") if p.is_file()]
    if not files:
        return None
    target = max(files, key=lambda p: p.stat().st_size)
    size = target.stat().st_size
    with open(target, "r+b") as fh:
        fh.truncate(size // 2)
    emit_fault(
        writer,
        {
            "fault": "torn-checkpoint",
            "site": "ckpt-truncate",
            "step": step,
            "path": str(target.relative_to(directory)),
            "bytes_before": size,
            "bytes_after": size // 2,
            "wall_time_s": round(time.time(), 3),
        },
    )
    return step, str(target)
