from glom_tpu.kernels.grouped_mlp import fused_grouped_ffw, fused_grouped_ffw_lm
from glom_tpu.kernels.consensus_update import fused_consensus_update

__all__ = [
    "fused_consensus_update",
    "fused_grouped_ffw",
    "fused_grouped_ffw_lm",
]
