from glom_tpu.utils.compat import install_pallas_tpu_compat

install_pallas_tpu_compat()  # pltpu.CompilerParams name on old jax

from glom_tpu.kernels.banded_consensus import banded_ragged_consensus
from glom_tpu.kernels.grouped_mlp import fused_grouped_ffw, fused_grouped_ffw_lm
from glom_tpu.kernels.consensus_update import fused_consensus_update

__all__ = [
    "banded_ragged_consensus",
    "fused_consensus_update",
    "fused_grouped_ffw",
    "fused_grouped_ffw_lm",
]
