from glom_tpu.kernels.grouped_mlp import fused_grouped_ffw

__all__ = ["fused_grouped_ffw"]
