"""Hand-rolled VJP over the WHOLE T-iteration GLOM loop.

Why this exists (measured, results/profiles/PROFILE.md round 3): with the
per-op custom_vjps the train step was ~86% Pallas kernels, and the
remaining ~6% device time was XLA glue BETWEEN them that op-local autodiff
cannot remove:

  * the per-iteration `concatenate([tokens, levels[:-1]])` feeding the
    bottom-up FFW (1.0 ms) and its transpose split in the backward
    (1.8 ms);
  * the cross-iteration dw/db gradient accumulation: each unrolled
    iteration's FFW backward emits fresh [G, d, f] f32 weight grads and
    XLA sums them with add_any HBM sweeps (2.5 ms);
  * the d(td) cotangent slice `dmean[:L-1]` copied between the consensus
    backward and the top-down FFW backward (1.2 ms).

This module replaces the scanned/unrolled loop with ONE jax.custom_vjp
whose forward and backward are Python loops over the same Pallas kernels,
re-plumbed so the glue disappears structurally:

  * the carry is an [L+1]-SLOT level-major array `ext` with the image
    tokens pinned in slot 0 and level l in slot l+1. Every consumer reads
    its slice via BlockSpec index-map OFFSETS on the shared buffer —
    bottom-up input = slots 0..L-1 (map g -> g), top-down input = slots
    2..L (map g -> g+2), consensus levels = slots 1..L (map g -> g+1) —
    so no concatenate/slice ever materializes (reference hot loop
    glom_pytorch/glom_pytorch.py:124-140 rebuilt without its cat).
  * the FFW backward kernels take INCOMING dw/db (and pos-emb da) f32
    accumulators and seed their m==0 init from them
    (grouped_mlp._mlp_bwd_tail inc=), so weight-gradient accumulation
    across the T iterations happens in-kernel, not in XLA add_any sweeps.
  * the consensus backward kernel reads THREE cotangent streams — the
    previous iteration's consensus dlevels, dx_bu (slot-shifted), and
    dx_td (slot-shifted) — via clamped index maps and combines them
    in-register; the top-down FFW backward then reads the resulting
    dmean's slots 0..L-2 directly off the [L, ...] buffer (grid has L-1
    groups), so the dmean[:L-1] slice never exists.

Scope: the flagship training regime — no remat (the loop IS unrolled),
return_all=False (the trainer's loss reads one iteration: the loop runs
exactly `iters` steps), single-tile consensus rows (n <= 512), tileable
FFW shapes. Everything else stays on models/core's scan paths.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glom_tpu.kernels.consensus_update import (
    _SMALL_BWD_N,
    _consensus_update_kernel,
    _fit_tile_b,
    _forward as _cons_forward,
    _pick_tile as _pick_cons_tile,
    _pick_tile_b as _pick_cons_tile_b,
    _small_bwd_math,
)
from glom_tpu.kernels.grouped_mlp import (
    _WS_BUDGET,
    _bwd_compiler_params,
    _bwd_ws,
    _fused_forward,
    _fused_forward_add,
    _mlp_bwd_kernel_saved,
    _mlp_bwd_kernel_saved_add,
    _mlp_bwd_tail,
    _mlp_kernel,
    _mlp_kernel_add,
    _pick_bwd_tile,
    _pick_tile,
    _tiled_add,
)
from glom_tpu.ops.ffw import GroupedFFWParams

_VMEM_64M = pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)
_VMEM_32M = pltpu.CompilerParams(vmem_limit_bytes=32 * 1024 * 1024)

# Per-iteration residual budget for the whole loop (saved ext carries +
# both FFW pre-activations + consensus stats, times `iters`). Above this
# the non-remat residual stack risks HBM exhaustion and the scan paths
# (whose save-pre gates handle their own budgets) take over. 10GB of a
# v5e's 16GB: batch 96 at the flagship (9.0GB of residuals) stays on the
# loop; batch 128 (12GB) falls back.
_RESIDUAL_BUDGET = 10 * 1024 * 1024 * 1024


def _ffw_fwd_ext(
    params: GroupedFFWParams,
    ext2: jnp.ndarray,  # [L+1, M, d] slot carry (reshaped level-major)
    offset: int,
    G: int,
    *,
    tile_m: int,
    interpret: bool,
    add: jnp.ndarray | None = None,
    save_pre: bool = True,
):
    """Grouped-FFW forward reading group g's input from carry slot
    g + offset — the index map IS the slice. Saves the pre-activation by
    default (the non-remat training forward); the REMAT forward passes
    save_pre=False so the [G, M, f] pre never hits HBM — the backward
    recomputes it per iteration via _pre_fwd_ext instead. (The no-grad
    primal uses grouped_mlp's plain forms.) Returns (out, pre|None)."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = params.w1.shape[-1]
    grid = (G, M // tile_m)
    out_shape = (
        jax.ShapeDtypeStruct((G, M, d), ext2.dtype),
        jax.ShapeDtypeStruct((G, M, f), ext2.dtype),
    )
    out_spec = (
        pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0)),
        pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)),
    )
    if not save_pre:
        out_shape, out_spec = out_shape[:1], out_spec[:1]
    x_spec = pl.BlockSpec(
        (1, tile_m, d), lambda g, m, _o=offset: (g + _o, m, 0)
    )
    w_specs = [
        pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),  # w1
        pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),  # b1
        pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),  # w2
        pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),  # b2
    ]
    if add is not None:
        out = pl.pallas_call(
            _mlp_kernel_add,
            out_shape=out_shape,
            grid=grid,
            in_specs=[x_spec, pl.BlockSpec(add.shape, lambda g, m: (0, 0))]
            + w_specs,
            out_specs=out_spec,
            compiler_params=_VMEM_64M,
            interpret=interpret,
        )(ext2, add, params.w1, params.b1[:, None, :], params.w2,
          params.b2[:, None, :])
    else:
        out = pl.pallas_call(
            _mlp_kernel,
            out_shape=out_shape,
            grid=grid,
            in_specs=[x_spec] + w_specs,
            out_specs=out_spec,
            compiler_params=_VMEM_64M,
            interpret=interpret,
        )(ext2, params.w1, params.b1[:, None, :], params.w2,
          params.b2[:, None, :])
    return out if save_pre else (out[0], None)


def _pre_kernel(x_ref, w1_ref, b1_ref, pre_ref):
    """First-matmul-only recompute: pre = x @ w1 + b1 in the compute dtype —
    bit-identical to the pre the training forward would have saved
    (_mlp_kernel computes it with the same f32-accumulate dot + cast)."""
    pre = jnp.dot(x_ref[0], w1_ref[0], preferred_element_type=jnp.float32)
    pre = pre + b1_ref[0].astype(jnp.float32)
    pre_ref[0] = pre.astype(x_ref.dtype)


def _pre_add_kernel(x_ref, a_ref, w1_ref, b1_ref, pre_ref):
    """_pre_kernel with the positional addend folded into the input load
    (matches _mlp_kernel_add's pre exactly)."""
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    pre = jnp.dot(xa, w1_ref[0], preferred_element_type=jnp.float32)
    pre = pre + b1_ref[0].astype(jnp.float32)
    pre_ref[0] = pre.astype(xa.dtype)


def _pre_fwd_ext(
    params: GroupedFFWParams,
    ext2: jnp.ndarray,  # [L+1, M, d] saved slot carry
    offset: int,
    G: int,
    *,
    tile_m: int,
    interpret: bool,
    add: jnp.ndarray | None = None,
):
    """REMAT-mode pre-activation recompute for one iteration: only the
    first matmul re-runs (the second matmul's output never feeds the
    backward — the consensus stats (m, l) are saved instead of recomputed),
    so the remat tax is HALF the FFW forward, not a full forward re-run."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = params.w1.shape[-1]
    grid = (G, M // tile_m)
    x_spec = pl.BlockSpec((1, tile_m, d), lambda g, m, _o=offset: (g + _o, m, 0))
    w1_spec = pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0))
    b1_spec = pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0))
    out_shape = jax.ShapeDtypeStruct((G, M, f), ext2.dtype)
    out_spec = pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0))
    if add is not None:
        return pl.pallas_call(
            _pre_add_kernel,
            out_shape=out_shape,
            grid=grid,
            in_specs=[
                x_spec, pl.BlockSpec(add.shape, lambda g, m: (0, 0)),
                w1_spec, b1_spec,
            ],
            out_specs=out_spec,
            compiler_params=_VMEM_64M,
            interpret=interpret,
        )(ext2, add, params.w1, params.b1[:, None, :])
    return pl.pallas_call(
        _pre_kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[x_spec, w1_spec, b1_spec],
        out_specs=out_spec,
        compiler_params=_VMEM_64M,
        interpret=interpret,
    )(ext2, params.w1, params.b1[:, None, :])


def _ffw_bwd_acc_kernel(
    x_ref, w1_ref, pre_ref, w2_ref, g_ref,
    dw1i_ref, db1i_ref, dw2i_ref, db2i_ref,
    dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
):
    """Saved-pre FFW backward with incoming weight-grad accumulators: the
    m==0 init seeds from the previous iteration's totals (see
    _mlp_bwd_tail inc=)."""
    _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), x_ref[0], g_ref[0], w1_ref[0],
        w2_ref[0], dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
        inc=(dw1i_ref, db1i_ref, dw2i_ref, db2i_ref),
    )


def _ffw_bwd_acc_add_kernel(
    x_ref, a_ref, w1_ref, pre_ref, w2_ref, g_ref,
    dw1i_ref, db1i_ref, dw2i_ref, db2i_ref, dai_ref,
    dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, da_ref,
):
    """_ffw_bwd_acc_kernel for the folded positional addend: the true layer
    input is x + tile(a), and da accumulates across the whole grid AND
    across loop iterations (seeded from dai at the first program)."""
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    dx32 = _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), xa, g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
        inc=(dw1i_ref, db1i_ref, dw2i_ref, db2i_ref),
    )
    tm, d = dx32.shape
    n = a_ref.shape[0]
    da_step = jnp.sum(dx32.reshape(tm // n, n, d), axis=0)
    first = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

    @pl.when(first)
    def _init_da():
        da_ref[...] = dai_ref[...] + da_step

    @pl.when(jnp.logical_not(first))
    def _accum_da():
        da_ref[...] += da_step


def _grid_mode() -> str:
    """Trace-time knob for the loop's FFW grid layout. 'split' (default):
    two pallas_calls per direction per iteration — the round-4 measured
    configuration. 'combined' (GLOM_LOOP_GRID=combined): ONE call over all
    2L-1 groups (td groups 0..L-2, bu groups L-1..2L-2), killing a kernel
    boundary per phase per iteration and giving Mosaic a single larger
    grid to overlap dw flushes across — VERDICT r4 item #5's 'fuse the
    bu/td backward grids'. Values are bit-identical (same per-group math,
    same accumulation order); promote to default only after the hardware
    A/B (scratch/ffw_bwd_sched_probe.py) measures >= split. A mid-session
    env flip between a forward and its cached backward cannot corrupt
    results: the residual tuple LENGTH encodes the layout (4 = combined,
    5 = split, 3 = remat, whose recompute is layout-agnostic)."""
    import os
    import warnings

    mode = os.environ.get("GLOM_LOOP_GRID", "split")
    if mode not in ("split", "combined"):
        # a typo in an A/B run must not silently measure split twice
        warnings.warn(
            f"GLOM_LOOP_GRID={mode!r} ignored (valid: split / combined); "
            "using split",
            stacklevel=3,
        )
        return "split"
    return mode


def _cat_params(td_params: GroupedFFWParams, bu_params: GroupedFFWParams):
    """td||bu group-axis concat, built ONCE per step (weights are loop
    invariants): [2L-1, ...] per leaf, ~0.1 ms of HBM traffic at the
    flagship vs 2·T kernel-boundary bubbles saved."""
    return GroupedFFWParams(
        jnp.concatenate([td_params.w1, bu_params.w1]),
        jnp.concatenate([td_params.b1, bu_params.b1]),
        jnp.concatenate([td_params.w2, bu_params.w2]),
        jnp.concatenate([td_params.b2, bu_params.b2]),
    )


def _cat_x_spec(tile_m: int, d: int, split: int):
    """x read for cat grids: td group g reads carry slot g+2, bu group
    g' = g-split reads slot g' (tokens pinned in slot 0)."""
    return pl.BlockSpec(
        (1, tile_m, d),
        lambda g, m, _s=split: (jnp.where(g < _s, g + 2, g - _s), m, 0),
    )


def _cat_addend(pos_emb: jnp.ndarray) -> jnp.ndarray:
    """[2n, d]: row-block 0 = the positional table (td groups), row-block
    1 = zeros (bu groups add nothing) — selected per group by the addend
    index map, so _mlp_kernel_add / _pre_add_kernel run UNCHANGED on the
    cat grid."""
    return jnp.concatenate([pos_emb, jnp.zeros_like(pos_emb)], axis=0)


def _cat_a_spec(n: int, d: int, split: int):
    return pl.BlockSpec(
        (n, d), lambda g, m, _s=split: (jnp.where(g < _s, 0, 1), 0)
    )


def _ffw_fwd_cat(
    wcat: GroupedFFWParams,
    ext2: jnp.ndarray,   # [L+1, M, d]
    a2: jnp.ndarray,     # [2n, d] padded addend (_cat_addend, hoisted)
    L: int,
    *,
    tile_m: int,
    interpret: bool,
    save_pre: bool = True,
):
    """Combined bu+td forward: one grid over 2L-1 groups. Returns
    (out_cat [G, M, d], pre_cat [G, M, f] | None)."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = wcat.w1.shape[-1]
    G, split = 2 * L - 1, L - 1
    n = a2.shape[0] // 2
    grid = (G, M // tile_m)
    out_shape = (
        jax.ShapeDtypeStruct((G, M, d), ext2.dtype),
        jax.ShapeDtypeStruct((G, M, f), ext2.dtype),
    )
    out_spec = (
        pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0)),
        pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)),
    )
    if not save_pre:
        out_shape, out_spec = out_shape[:1], out_spec[:1]
    out = pl.pallas_call(
        _mlp_kernel_add,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            _cat_x_spec(tile_m, d, split),
            _cat_a_spec(n, d, split),
            pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),
            pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),
            pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),
            pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),
        ],
        out_specs=out_spec,
        compiler_params=_VMEM_64M,
        interpret=interpret,
    )(ext2, a2, wcat.w1, wcat.b1[:, None, :], wcat.w2, wcat.b2[:, None, :])
    return out if save_pre else (out[0], None)


def _pre_fwd_cat(
    wcat: GroupedFFWParams,
    ext2: jnp.ndarray,
    a2: jnp.ndarray,     # [2n, d] padded addend (_cat_addend, hoisted)
    L: int,
    *,
    tile_m: int,
    interpret: bool,
):
    """Remat-mode pre recompute on the cat grid (first matmul only)."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = wcat.w1.shape[-1]
    G, split = 2 * L - 1, L - 1
    n = a2.shape[0] // 2
    return pl.pallas_call(
        _pre_add_kernel,
        out_shape=jax.ShapeDtypeStruct((G, M, f), ext2.dtype),
        grid=(G, M // tile_m),
        in_specs=[
            _cat_x_spec(tile_m, d, split),
            _cat_a_spec(n, d, split),
            pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),
            pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)),
        compiler_params=_VMEM_64M,
        interpret=interpret,
    )(ext2, a2, wcat.w1, wcat.b1[:, None, :])


def _ffw_bwd_cat_acc_kernel(
    x_ref, a_ref, w1_ref, pre_ref, w2_ref, g_ref,
    dw1i_ref, db1i_ref, dw2i_ref, db2i_ref, dai_ref,
    dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, da_ref,
    *, split,
):
    """Chained cat-grid backward: like _ffw_bwd_acc_add_kernel, but the da
    reduction is MASKED to the td groups (the zero-addend trick keeps the
    matmul math identical for bu groups, but their dx must not leak into
    d(pos))."""
    gid = pl.program_id(0)
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    dx32 = _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), xa, g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
        inc=(dw1i_ref, db1i_ref, dw2i_ref, db2i_ref),
    )
    tm, d = dx32.shape
    n = a_ref.shape[0]
    da_step = jnp.where(
        gid < split,
        jnp.sum(dx32.reshape(tm // n, n, d), axis=0),
        0.0,
    )
    first = (gid == 0) & (pl.program_id(1) == 0)

    @pl.when(first)
    def _init_da():
        da_ref[...] = dai_ref[...] + da_step

    @pl.when(jnp.logical_not(first))
    def _accum_da():
        da_ref[...] += da_step


def _ffw_bwd_cat_kernel(
    x_ref, a_ref, w1_ref, pre_ref, w2_ref, g_ref,
    dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, da_ref,
    *, split,
):
    """Unchained cat-grid backward (fresh dw per iteration, XLA adds)."""
    gid = pl.program_id(0)
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    dx32 = _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), xa, g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
    )
    tm, d = dx32.shape
    n = a_ref.shape[0]
    da_step = jnp.where(
        gid < split,
        jnp.sum(dx32.reshape(tm // n, n, d), axis=0),
        0.0,
    )
    first = (gid == 0) & (pl.program_id(1) == 0)

    @pl.when(first)
    def _init_da():
        da_ref[...] = da_step

    @pl.when(jnp.logical_not(first))
    def _accum_da():
        da_ref[...] += da_step


def _ffw_bwd_cat(
    wcat: GroupedFFWParams,
    ext2: jnp.ndarray,       # [L+1, M, d] saved carry
    pre_cat: jnp.ndarray,    # [G, M, f]
    gcot2: jnp.ndarray,      # [L, M, d] dmean
    acc: GroupedFFWParams,   # [G, ...] incoming f32 accumulators
    a2: jnp.ndarray,         # [2n, d] padded addend (_cat_addend, hoisted)
    da_in: jnp.ndarray,
    L: int,
    *,
    tile_m: int,
    interpret: bool,
    chain: bool,
):
    """Combined bu+td backward: one grid over 2L-1 groups. td group g
    reads cotangent dmean slot g; bu group g' reads slot g'. Returns
    (accumulated grads [G, ...], dx_cat [G, M, d], da)."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = wcat.w1.shape[-1]
    G, split = 2 * L - 1, L - 1
    n = a2.shape[0] // 2
    f32 = jnp.float32
    grid = (G, M // tile_m)
    row_spec = pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0))
    cot_spec = pl.BlockSpec(
        (1, tile_m, d),
        lambda g, m, _s=split: (jnp.where(g < _s, g, g - _s), m, 0),
    )
    acc_specs = [
        pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),
    ]
    da_spec = pl.BlockSpec((n, d), lambda g, m: (0, 0))
    out_shapes = (
        jax.ShapeDtypeStruct((G, M, d), ext2.dtype),
        jax.ShapeDtypeStruct((G, d, f), f32),
        jax.ShapeDtypeStruct((G, 1, f), f32),
        jax.ShapeDtypeStruct((G, f, d), f32),
        jax.ShapeDtypeStruct((G, 1, d), f32),
        jax.ShapeDtypeStruct((n, d), f32),
    )
    out_specs = (row_spec,) + tuple(acc_specs) + (da_spec,)
    common = [
        _cat_x_spec(tile_m, d, split),
        _cat_a_spec(n, d, split),  # pos row for td groups, zeros for bu
        pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),  # w1
        pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)),  # pre
        pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),  # w2
        cot_spec,
    ]
    compiler_params = (
        _VMEM_64M if chain
        else _bwd_compiler_params(tile_m, d, f, ext2.dtype.itemsize)
    )
    if chain:
        dx, dw1, db1, dw2, db2, da = pl.pallas_call(
            partial(_ffw_bwd_cat_acc_kernel, split=split),
            out_shape=out_shapes,
            grid=grid,
            in_specs=common + acc_specs + [da_spec],
            out_specs=out_specs,
            compiler_params=compiler_params,
            interpret=interpret,
        )(ext2, a2, wcat.w1, pre_cat, wcat.w2, gcot2,
          acc.w1, acc.b1, acc.w2, acc.b2, da_in)
        return GroupedFFWParams(dw1, db1, dw2, db2), dx, da
    dx, dw1, db1, dw2, db2, da = pl.pallas_call(
        partial(_ffw_bwd_cat_kernel, split=split),
        out_shape=out_shapes,
        grid=grid,
        in_specs=common,
        out_specs=out_specs,
        compiler_params=compiler_params,
        interpret=interpret,
    )(ext2, a2, wcat.w1, pre_cat, wcat.w2, gcot2)
    fresh = GroupedFFWParams(dw1, db1, dw2, db2)
    return jax.tree_util.tree_map(jnp.add, acc, fresh), dx, da_in + da


def _chain_ws_ok(bt: int, d: int, f: int, itemsize: int, n: int) -> bool:
    """Can the accumulator-CHAINED backward kernels fit the working-set
    budget? Chaining adds the incoming dw1/dw2 f32 blocks (2*d*f*4) and
    the in+out da pair (n*d*8) to the per-op backward working set. At the
    flagship (d=512, f=2048) that is ~34.5MB — fits; at the pod per-TP-rank
    shape (d=1024, f=2048) it is ~58.7MB > the 48MB budget, so the loop
    there runs the UNCHAINED variant (fresh per-iteration dw, XLA adds) —
    the same per-op kernel footprint that measured 75-78M of Mosaic stack
    under the 100MB grant on silicon."""
    return _bwd_ws(bt, d, f, itemsize) + 2 * d * f * 4 + n * d * 8 <= _WS_BUDGET


def _ffw_bwd_ext(
    params: GroupedFFWParams,
    ext2: jnp.ndarray,      # [L+1, M, d] saved carry (this iteration's input)
    offset: int,
    G: int,
    pre: jnp.ndarray,       # [G, M, f] saved pre-activation
    gcot2: jnp.ndarray,     # [L, M, d] dmean — G <= L reads slots 0..G-1
    acc: GroupedFFWParams,  # incoming f32 dw/db accumulators
    *,
    tile_m: int,
    interpret: bool,
    add: jnp.ndarray | None = None,
    da_in: jnp.ndarray | None = None,
    chain: bool = True,
):
    """One iteration's FFW backward: x via slot-offset map, cotangent read
    directly off the full dmean buffer (the td call's G = L-1 grid IS the
    [:L-1] slice), dw/db (and da) chained through incoming accumulators.

    chain=False (shapes where _chain_ws_ok fails, e.g. the pod per-TP-rank
    d=1024) runs the per-op saved-pre kernels with the SAME slot-offset /
    direct-dmean specs — the concat/slice glue stays dead — and the
    cross-iteration dw/da accumulation happens here in XLA adds instead of
    in-kernel seeding. Returns the same (accumulated grads, dx, da)."""
    M, d = ext2.shape[1], ext2.shape[2]
    f = params.w1.shape[-1]
    f32 = jnp.float32
    grid = (G, M // tile_m)
    x_spec = pl.BlockSpec((1, tile_m, d), lambda g, m, _o=offset: (g + _o, m, 0))
    row_spec = pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0))
    acc_specs = [
        pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),
        pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),
    ]
    out_shapes = (
        jax.ShapeDtypeStruct((G, M, d), ext2.dtype),  # dx
        jax.ShapeDtypeStruct((G, d, f), f32),
        jax.ShapeDtypeStruct((G, 1, f), f32),
        jax.ShapeDtypeStruct((G, f, d), f32),
        jax.ShapeDtypeStruct((G, 1, d), f32),
    )
    out_specs = (row_spec,) + tuple(acc_specs)
    common = [
        x_spec,  # x (slot-offset)
        pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),  # w1
        pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)),  # pre
        pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),  # w2
        row_spec,  # g cotangent (dmean slots 0..G-1)
    ]
    compiler_params = (
        _VMEM_64M if chain
        else _bwd_compiler_params(tile_m, d, f, ext2.dtype.itemsize)
    )
    if add is not None:
        n = add.shape[0]
        a_spec = pl.BlockSpec(add.shape, lambda g, m: (0, 0))
        da_spec = pl.BlockSpec((n, d), lambda g, m: (0, 0))
        if chain:
            dx, dw1, db1, dw2, db2, da = pl.pallas_call(
                _ffw_bwd_acc_add_kernel,
                out_shape=out_shapes + (jax.ShapeDtypeStruct((n, d), f32),),
                grid=grid,
                in_specs=[common[0], a_spec] + common[1:] + acc_specs + [da_spec],
                out_specs=out_specs + (da_spec,),
                compiler_params=compiler_params,
                interpret=interpret,
            )(ext2, add, params.w1, pre, params.w2, gcot2,
              acc.w1, acc.b1, acc.w2, acc.b2, da_in)
            return GroupedFFWParams(dw1, db1, dw2, db2), dx, da
        dx, dw1, db1, dw2, db2, da = pl.pallas_call(
            _mlp_bwd_kernel_saved_add,
            out_shape=out_shapes + (jax.ShapeDtypeStruct((n, d), f32),),
            grid=grid,
            in_specs=[common[0], a_spec] + common[1:],
            out_specs=out_specs + (da_spec,),
            compiler_params=compiler_params,
            interpret=interpret,
        )(ext2, add, params.w1, pre, params.w2, gcot2)
        fresh = GroupedFFWParams(dw1, db1, dw2, db2)
        return (
            jax.tree_util.tree_map(jnp.add, acc, fresh),
            dx,
            da_in + da,
        )
    if chain:
        dx, dw1, db1, dw2, db2 = pl.pallas_call(
            _ffw_bwd_acc_kernel,
            out_shape=out_shapes,
            grid=grid,
            in_specs=common + acc_specs,
            out_specs=out_specs,
            compiler_params=compiler_params,
            interpret=interpret,
        )(ext2, params.w1, pre, params.w2, gcot2,
          acc.w1, acc.b1, acc.w2, acc.b2)
        return GroupedFFWParams(dw1, db1, dw2, db2), dx, None
    dx, dw1, db1, dw2, db2 = pl.pallas_call(
        _mlp_bwd_kernel_saved,
        out_shape=out_shapes,
        grid=grid,
        in_specs=common,
        out_specs=out_specs,
        compiler_params=compiler_params,
        interpret=interpret,
    )(ext2, params.w1, pre, params.w2, gcot2)
    fresh = GroupedFFWParams(dw1, db1, dw2, db2)
    return jax.tree_util.tree_map(jnp.add, acc, fresh), dx, None


def _cons_fwd_ext(
    ext: jnp.ndarray,   # [L+1, B, n, d] slot carry
    bu: jnp.ndarray,    # [L, B, n, d], or the [2L-1, ...] cat buffer
    td: jnp.ndarray,    # [L-1, B, n, d], or the same cat buffer
    *,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool,
    cat: bool = False,
):
    """Fused consensus+mean update on the slot carry: level g's q/k/v read
    slot g+1, and the output writes slots 1..L of a fresh [L+1] buffer
    (slot 0 is re-pinned to the tokens by the caller's in-place
    dynamic_update_slice — the buffer's only other use). Always emits the
    (m, l) stats — the only caller is the training forward.

    cat=True: bu and td are the SAME [2L-1, B, n, d] combined-grid buffer
    (td groups in slots 0..L-2, bu in L-1..2L-2); only the index maps
    change — no slicing/copying of the cat buffer ever materializes."""
    Lp1, B, n, d = ext.shape
    L = Lp1 - 1
    tile_i = _pick_cons_tile(n)
    tile_j = _pick_cons_tile(n, cap=512 if radius <= 0 else 256)
    tile_b = _pick_cons_tile_b(
        B, n, d, tile_i, tile_j, ext.dtype.itemsize, streamed=False
    )
    kw = dict(
        levels_count=L, side=side, radius=float(radius),
        attend_self=attend_self, tile_i=tile_i, tile_j=tile_j, n=n,
    )

    def lv_spec(last):
        return pl.BlockSpec(
            (1, tile_b, tile_i, last), lambda g, b, i: (g + 1, b, i, 0)
        )

    def g_spec(last):
        return pl.BlockSpec(
            (1, tile_b, tile_i, last), lambda g, b, i: (g, b, i, 0)
        )

    stat_shape = jax.ShapeDtypeStruct((L, B, n, 1), jnp.float32)
    out_shape = (
        jax.ShapeDtypeStruct((Lp1, B, n, d), ext.dtype), stat_shape, stat_shape
    )
    out_spec = (lv_spec(d), g_spec(1), g_spec(1))
    bu_off = L - 1 if cat else 0  # bu groups live at cat slots L-1..2L-2
    in_specs = [
        lv_spec(d),  # x (self tile): slot g+1
        pl.BlockSpec(
            (1, tile_b, n, d), lambda g, b, i: (g + 1, b, 0, 0)
        ),  # kv rows: slot g+1
        pl.BlockSpec(
            (1, tile_b, tile_i, d),
            lambda g, b, i, _o=bu_off: (_o + g, b, i, 0),
        ),  # bu
        pl.BlockSpec(
            (1, tile_b, tile_i, d),
            lambda g, b, i, _L=L: (jnp.minimum(g, _L - 2), b, i, 0),
        ),  # td (clamped top, masked in-kernel; cat slots 0..L-2 ARE td)
    ]
    return pl.pallas_call(
        partial(_consensus_update_kernel, **kw),
        out_shape=out_shape,
        grid=(L, B // tile_b, n // tile_i),
        in_specs=in_specs,
        out_specs=out_spec,
        interpret=interpret,
    )(ext, ext, bu, td)


def _cons_bwd_combine_kernel(
    x_ref, dg_ref, *refs,
    side, radius, attend_self, n, levels_count,
):
    """Single-tile consensus backward with the three cotangent streams
    combined in-register: the complete output cotangent of level g is

        dg[g] + dx_bu[g+1] (g < L-1)  + dx_td[g-1] (g >= 1)

    (bu input slot g+1 is level g for g <= L-2; td input slot g+2 is level
    g+1) — read via clamped index maps and masked here, so the XLA
    pad+add sweeps between backward kernels disappear. Emits the complete
    consensus dlevels AND dmean (= combined cotangent / div)."""
    dlv_ref, dmean_ref = refs[-2:]
    ins = refs[:-2]
    f32 = jnp.float32
    g_id = pl.program_id(0)
    cot = dg_ref[0].astype(f32)
    if len(ins) == 4:
        dxbu_ref, dxtd_ref, m_ref, l_ref = ins
        cot = cot + jnp.where(
            g_id < levels_count - 1, dxbu_ref[0].astype(f32), 0.0
        )
        cot = cot + jnp.where(g_id >= 1, dxtd_ref[0].astype(f32), 0.0)
    else:
        m_ref, l_ref = ins
    div = jnp.where(g_id == levels_count - 1, 3.0, 4.0)
    dcons = cot / div
    dlv = _small_bwd_math(
        x_ref[0], dcons, m_ref[0], l_ref[0],
        side=side, radius=radius, attend_self=attend_self, n=n,
    )
    dlv_ref[0] = dlv.astype(dlv_ref.dtype)
    dmean_ref[0] = dcons.astype(dmean_ref.dtype)


def _cons_bwd_ext(
    ext: jnp.ndarray,            # [L+1, B, n, d] saved carry
    m: jnp.ndarray,
    l: jnp.ndarray,
    dg: jnp.ndarray,             # [L, B, n, d] consensus-dlv cotangent stream
    dx_bu: jnp.ndarray | None,   # [L, B, n, d] (slot layout) or None
    dx_td: jnp.ndarray | None,   # [L-1, B, n, d] or None
    *,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool,
    cat: bool = False,
):
    """cat=True: dx_bu and dx_td are the SAME [2L-1, B, n, d] combined-grid
    dx buffer (td cotangents in slots 0..L-2, bu in L-1..2L-2); the bu
    stream's index map shifts by L-1, the td stream's already lands in the
    right slots."""
    Lp1, B, n, d = ext.shape
    L = Lp1 - 1
    itemsize = ext.dtype.itemsize
    tile_b = _fit_tile_b(
        B,
        lambda tb: 3 * tb * n * n * 4 + 8 * tb * n * d * (itemsize + 1),
    )

    def spec(last, map_fn):
        return pl.BlockSpec((1, tile_b, n, last), map_fn)

    ident = lambda g, b: (g, b, 0, 0)
    in_specs = [spec(d, lambda g, b: (g + 1, b, 0, 0)), spec(d, ident)]
    ins = [ext, dg]
    if dx_bu is not None:
        bu_off = L - 1 if cat else 0
        in_specs += [
            spec(
                d,
                lambda g, b, _L=L, _o=bu_off: (
                    _o + jnp.minimum(g + 1, _L - 1), b, 0, 0
                ),
            ),
            spec(d, lambda g, b: (jnp.maximum(g - 1, 0), b, 0, 0)),
        ]
        ins += [dx_bu, dx_td]
    in_specs += [spec(1, ident), spec(1, ident)]
    ins += [m, l]
    dlv, dmean = pl.pallas_call(
        partial(
            _cons_bwd_combine_kernel,
            side=side, radius=float(radius), attend_self=attend_self,
            n=n, levels_count=L,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((L, B, n, d), ext.dtype),
            jax.ShapeDtypeStruct((L, B, n, d), ext.dtype),
        ),
        grid=(L, B // tile_b),
        in_specs=in_specs,
        out_specs=(spec(d, ident), spec(d, ident)),
        compiler_params=_VMEM_32M,
        interpret=interpret,
    )(*ins)
    return dlv, dmean


def loop_supported(
    L: int, B: int, n: int, d: int, f: int, itemsize: int, iters: int,
    pos_n: int, remat: bool = False,
) -> bool:
    """Static eligibility for the hand-rolled loop VJP (the flagship
    training regime); callers fall back to the scan paths otherwise.

    remat=True is the recompute-per-iteration mode (BASELINE config 5's
    "ckpt over iters"): the residual stack drops the [G, M, f]
    pre-activations — the dominant term, (2L-1)·M·f vs (L+1)·M·d, ~6x at
    mult=4 — because the backward re-runs the FIRST FFW matmul per
    iteration (_pre_fwd_ext); only the carries and the tiny consensus
    stats are saved."""
    M = B * n
    tile = _pick_tile(M, d, f, itemsize)
    bt = _pick_bwd_tile(M, d, f, itemsize)
    if iters < 1 or tile is None or bt is None:
        return False
    if d % 128 != 0 or f % 128 != 0 or n % 8 != 0 or L < 2:
        return False
    if n > _SMALL_BWD_N:
        return False
    # pos-emb fold constraints (the td kernels tile the addend per row tile)
    if pos_n != n or M % n or tile % n or bt % n:
        return False
    # the backward must fit EITHER accumulator-chained (the flagship
    # configuration) or unchained (per-op footprint + the resident da —
    # the pod per-TP-rank d=1024 shape; see _chain_ws_ok)
    if not _chain_ws_ok(bt, d, f, itemsize, n) and (
        _bwd_ws(bt, d, f, itemsize) + n * d * 4 > _WS_BUDGET
    ):
        return False
    per_iter = (
        (L + 1) * M * d * itemsize          # saved carry
        + 2 * L * M * 4                     # consensus stats
    )
    if not remat:
        per_iter += (2 * L - 1) * M * f * itemsize  # both FFW pre-activations
    return iters * per_iter <= _RESIDUAL_BUDGET


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def fused_glom_loop(
    bu_params: GroupedFFWParams,
    td_params: GroupedFFWParams,
    pos_emb: jnp.ndarray,    # [n, d]
    tokens: jnp.ndarray,     # [B, n, d]
    levels0: jnp.ndarray,    # [L, B, n, d] level-major
    iters: int,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool = False,
    remat: bool = False,
):
    """Run `iters` GLOM column updates and return the final level-major
    [L, B, n, d] state.

    PRIMAL path (this body; jax runs it only when NOT differentiating):
    the plain level-major iteration with an [L] carry — the slot machinery
    exists purely for the BACKWARD's benefit, and for pure forwards its
    per-iteration slot-0 re-pin and final [1:] slice measured a ~2%
    forward-bench tax (13.9k vs 14.2k col-iters/s). The [L+1]-slot form
    lives in _loop_fwd, which runs under jax.vjp/grad.

    remat=True (identical math; static) switches the VJP to
    recompute-per-iteration: _loop_fwd saves only (carry, consensus stats)
    and _loop_bwd re-runs the first FFW matmul per iteration — BASELINE
    config 5's checkpoint-over-iters regime without the scan-path glue."""
    L = levels0.shape[0]
    B, n, d = tokens.shape
    M = B * n
    tile_m = _pick_tile(M, d, bu_params.w1.shape[-1], tokens.dtype.itemsize)
    lv = levels0
    tokens_lm = tokens[None]
    for _ in range(iters):
        bu_in = jnp.concatenate([tokens_lm, lv[:-1]], axis=0)
        bu = _fused_forward(
            bu_params, bu_in.reshape(L, M, d), tile_m=tile_m,
            interpret=interpret,
        ).reshape(L, B, n, d)
        td = _fused_forward_add(
            td_params, lv[1:].reshape(L - 1, M, d), pos_emb,
            tile_m=tile_m, interpret=interpret,
        ).reshape(L - 1, B, n, d)
        lv = _cons_forward(
            lv, bu, td,
            side=side, radius=radius, attend_self=attend_self,
            interpret=interpret,
        )
    return lv


def _loop_fwd(
    bu_params, td_params, pos_emb, tokens, levels0,
    iters, side, radius, attend_self, interpret, remat=False,
):
    L = levels0.shape[0]
    B, n, d = tokens.shape
    ext = jnp.concatenate([tokens[None], levels0], axis=0)
    ext2_shape = (L + 1, B * n, d)
    tile_m = _pick_tile(B * n, d, bu_params.w1.shape[-1], tokens.dtype.itemsize)
    combined = _grid_mode() == "combined"
    wcat = _cat_params(td_params, bu_params) if combined else None
    a2 = _cat_addend(pos_emb) if combined else None  # loop-invariant
    saved = []
    for _ in range(iters):
        ext2 = ext.reshape(ext2_shape)
        if combined:
            out_cat, pre_cat = _ffw_fwd_cat(
                wcat, ext2, a2, L, tile_m=tile_m, interpret=interpret,
                save_pre=not remat,
            )
            cat4 = out_cat.reshape(2 * L - 1, B, n, d)
            new_ext, m, l = _cons_fwd_ext(
                ext, cat4, cat4,
                side=side, radius=radius, attend_self=attend_self,
                interpret=interpret, cat=True,
            )
            # Residual tuple LENGTH encodes the grid layout for _loop_bwd:
            # 4 = combined, 5 = split, 3 = remat (layout-agnostic).
            saved.append((ext, m, l) if remat else (ext, pre_cat, m, l))
        else:
            bu, pre_bu = _ffw_fwd_ext(
                bu_params, ext2, 0, L, tile_m=tile_m, interpret=interpret,
                save_pre=not remat,
            )
            td, pre_td = _ffw_fwd_ext(
                td_params, ext2, 2, L - 1, tile_m=tile_m, interpret=interpret,
                add=pos_emb, save_pre=not remat,
            )
            new_ext, m, l = _cons_fwd_ext(
                ext, bu.reshape(L, B, n, d), td.reshape(L - 1, B, n, d),
                side=side, radius=radius, attend_self=attend_self,
                interpret=interpret,
            )
            # Remat mode saves only the carry + the tiny [L, B, n, 1]
            # stats; the pre-activations (the dominant residual) are
            # recomputed per iteration in _loop_bwd.
            saved.append(
                (ext, m, l) if remat else (ext, pre_bu, pre_td, m, l)
            )
        ext = jax.lax.dynamic_update_slice(new_ext, tokens[None], (0, 0, 0, 0))
    return ext[1:], (bu_params, td_params, pos_emb, tuple(saved))


def _loop_bwd(iters, side, radius, attend_self, interpret, remat, res, g):
    bu_params, td_params, pos_emb, saved = res
    L_, B, n, d = g.shape
    L = L_
    M = B * n
    f32 = jnp.float32
    f_bu = bu_params.w1.shape[-1]
    bt = _pick_bwd_tile(M, d, f_bu, g.dtype.itemsize)

    zeros_acc = lambda p: GroupedFFWParams(
        jnp.zeros(p.w1.shape, f32),
        jnp.zeros((p.b1.shape[0], 1, p.b1.shape[1]), f32),
        jnp.zeros(p.w2.shape, f32),
        jnp.zeros((p.b2.shape[0], 1, p.b2.shape[1]), f32),
    )
    acc_bu = zeros_acc(bu_params)
    acc_td = zeros_acc(td_params)
    da = jnp.zeros((n, d), f32)
    dtok = jnp.zeros((B, n, d), f32)
    dlv = g
    dx_bu = dx_td = None

    tile_fwd = _pick_tile(M, d, f_bu, g.dtype.itemsize)
    chain = _chain_ws_ok(bt, d, f_bu, g.dtype.itemsize, n)

    # Grid layout from the residual STRUCTURE (4-tuple = combined,
    # 5-tuple = split); remat residuals (3-tuple) are layout-agnostic —
    # the recompute form follows the env knob, values identical.
    combined = len(saved[0]) == 4 or (
        len(saved[0]) == 3 and _grid_mode() == "combined"
    )
    if combined:
        G, split = 2 * L - 1, L - 1
        wcat = _cat_params(td_params, bu_params)
        a2 = _cat_addend(pos_emb)  # loop-invariant, built once
        acc_cat = GroupedFFWParams(
            jnp.zeros((G, d, f_bu), f32),
            jnp.zeros((G, 1, f_bu), f32),
            jnp.zeros((G, f_bu, d), f32),
            jnp.zeros((G, 1, d), f32),
        )
        dx_cat4 = None
        for t in reversed(range(iters)):
            if len(saved[t]) == 3:
                ext, m, l = saved[t]
                pre_cat = _pre_fwd_cat(
                    wcat, ext.reshape(L + 1, M, d), a2, L,
                    tile_m=tile_fwd, interpret=interpret,
                )
            else:
                ext, pre_cat, m, l = saved[t]
            dlv, dmean = _cons_bwd_ext(
                ext, m, l, dlv, dx_cat4, dx_cat4,
                side=side, radius=radius, attend_self=attend_self,
                interpret=interpret, cat=True,
            )
            ext2 = ext.reshape(L + 1, M, d)
            dmean2 = dmean.reshape(L, M, d)
            acc_cat, dx_cat, da = _ffw_bwd_cat(
                wcat, ext2, pre_cat, dmean2, acc_cat, a2, da, L,
                tile_m=bt, interpret=interpret, chain=chain,
            )
            dx_cat4 = dx_cat.reshape(G, B, n, d)
            dtok = dtok + dx_cat4[split].astype(f32)
        dx_bu = dx_cat4[split:]
        dx_td = dx_cat4[:split]
        acc_td = jax.tree_util.tree_map(lambda t: t[:split], acc_cat)
        acc_bu = jax.tree_util.tree_map(lambda t: t[split:], acc_cat)
    else:
        for t in reversed(range(iters)):
            if remat:
                ext, m, l = saved[t]
                ext2_r = ext.reshape(L + 1, M, d)
                pre_bu = _pre_fwd_ext(
                    bu_params, ext2_r, 0, L, tile_m=tile_fwd,
                    interpret=interpret,
                )
                pre_td = _pre_fwd_ext(
                    td_params, ext2_r, 2, L - 1, tile_m=tile_fwd,
                    interpret=interpret, add=pos_emb,
                )
            else:
                ext, pre_bu, pre_td, m, l = saved[t]
            dlv, dmean = _cons_bwd_ext(
                ext, m, l, dlv, dx_bu, dx_td,
                side=side, radius=radius, attend_self=attend_self,
                interpret=interpret,
            )
            ext2 = ext.reshape(L + 1, M, d)
            dmean2 = dmean.reshape(L, M, d)
            acc_td, dx_td2, da = _ffw_bwd_ext(
                td_params, ext2, 2, L - 1, pre_td, dmean2, acc_td,
                tile_m=bt, interpret=interpret, add=pos_emb, da_in=da,
                chain=chain,
            )
            acc_bu, dx_bu2, _ = _ffw_bwd_ext(
                bu_params, ext2, 0, L, pre_bu, dmean2, acc_bu,
                tile_m=bt, interpret=interpret, chain=chain,
            )
            dx_bu = dx_bu2.reshape(L, B, n, d)
            dx_td = dx_td2.reshape(L - 1, B, n, d)
            dtok = dtok + dx_bu[0].astype(f32)

    # Final combine at the loop entry: d(levels0) gathers all three streams.
    # Written as slice-adds + one concatenate (NOT .at[].add, which lowers
    # to a slow TPU scatter-add): XLA fuses the slices into the adds, so
    # each stream is read once and the result written once. The leading
    # f32 cast keeps the 3-term middle sum single-rounded (fused into the
    # adds; the final astype keeps the HBM write in the carry dtype).
    if L > 2:
        dlv0 = jnp.concatenate(
            [
                dlv[:1].astype(f32) + dx_bu[1:2],
                dlv[1 : L - 1].astype(f32) + dx_bu[2:] + dx_td[: L - 2],
                dlv[L - 1 :].astype(f32) + dx_td[L - 2 :],
            ],
            axis=0,
        )
    else:
        dlv0 = jnp.concatenate(
            [dlv[:1].astype(f32) + dx_bu[1:2], dlv[1:].astype(f32) + dx_td],
            axis=0,
        )

    def cast_grads(acc, p):
        return GroupedFFWParams(
            acc.w1.astype(p.w1.dtype),
            acc.b1[:, 0].astype(p.b1.dtype),
            acc.w2.astype(p.w2.dtype),
            acc.b2[:, 0].astype(p.b2.dtype),
        )

    return (
        cast_grads(acc_bu, bu_params),
        cast_grads(acc_td, td_params),
        da.astype(pos_emb.dtype),
        dtok.astype(g.dtype),
        dlv0.astype(g.dtype),
    )


fused_glom_loop.defvjp(_loop_fwd, _loop_bwd)
