"""Pallas TPU kernel: block-banded ragged consensus attention that reads
k/v PAGES in place.

The ragged serving path packs mixed-resolution rows page-aligned onto a
flat [T, L, d] token axis; consensus attention restricts each token to
its own row's page band of W = full-res-pages x page_tokens slots
(serve/early_exit.py). The jnp reference routes still build a duplicated
k/v working set per iteration — W column states per token (windowed) or
per page (banded). This kernel removes the copy entirely: one program
per (query page p, band page j) streams the band's k/v pages straight
from the flat state via a scalar-prefetched band-start map, with a
flash-style ONLINE softmax over j — the only per-program residency is
one [page_tokens, L, d] q/k/v tile and the f32 VMEM accumulators. Peak
ragged working set drops to the pages themselves, which is what lets
the largest admitted ragged signature per chip grow (--banded-ab).

Mask semantics are the reference routes' exactly: slots past the row's
real length hard-masked to -3e38, the self slot soft-masked to -5e-4
when attend_self=False, both computed in-register from iota + the
prefetched per-page (band start, row length) scalars. Rows occupy whole
pages with page-aligned starts, so both scalars are constant within a
page — the precondition the banded decomposition rests on.

Parity contract: kernel-parity TOLERANCE against the jnp banded route
(the fused dense route's contract — an online softmax reorders the
reduction), NOT the bitwise bar; the jnp banded route is the one proven
bitwise against the windowed gather at threshold 0. Off-TPU (and not
interpret=True) the wrapper falls back to the jnp banded reference, so
CPU serving keeps the bitwise contract end to end.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE

_NEG_MAX = float(jnp.finfo(jnp.float32).min)


def _banded_kernel(
    band_ref,   # [P] int32 scalar-prefetch: band's first page per page
    len_ref,    # [P] int32 scalar-prefetch: row length per page
    q_ref,      # [1, pt, L, d] query page
    kv_ref,     # [1, pt, L, d] band page j (k and v read from ONE ref)
    o_ref,      # [1, pt, L, d] output page
    m_ref,      # [pt, L, 1] f32 scratch: running max
    l_ref,      # [pt, L, 1] f32 scratch: running sum
    acc_ref,    # [pt, L, d] f32 scratch: running weighted values
    *,
    pt: int,
    n_band: int,
    attend_self: bool,
    scale: float,
):
    p = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_MAX, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32)                       # [pt, L, d]
    kv = kv_ref[0].astype(jnp.float32)                     # [pt, L, d]
    # The one consensus k convention: q/v raw, k L2-normalized
    # (helpers.l2norm — x / max(||x||, eps)).
    norm = jnp.sqrt(jnp.sum(kv * kv, axis=-1, keepdims=True))
    k = kv / jnp.maximum(norm, 1e-12)

    # s[l, q, u] = q[q, l, :] . k[u, l, :]
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((1,), (1,))),
        preferred_element_type=jnp.float32,
    ) * scale                                              # [L, pt, pt]

    u = jax.lax.broadcasted_iota(jnp.int32, (pt, pt), 1)   # k slot in page
    qq = jax.lax.broadcasted_iota(jnp.int32, (pt, pt), 0)  # q slot in page
    w_slot = j * pt + u                                    # band offset
    if not attend_self:
        # Self slot: band-global position == query's flat token index.
        self_slot = (band_ref[p] + j) * pt + u == p * pt + qq
        s = jnp.where(self_slot[None], TOKEN_ATTEND_SELF_VALUE, s)
    s = jnp.where((w_slot < len_ref[p])[None], s, _NEG_MAX)

    s = jnp.transpose(s, (1, 0, 2))                        # [pt, L, pt]
    m_prev = m_ref[...][..., 0]                            # [pt, L]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    corr = jnp.exp(m_prev - m_new)
    pexp = jnp.exp(s - m_new[..., None])                   # [pt, L, pt]
    l_ref[...] = (
        l_ref[...][..., 0] * corr + jnp.sum(pexp, axis=-1)
    )[..., None]
    pv = jax.lax.dot_general(
        pexp, kv, (((2,), (0,)), ((1,), (1,))),
        preferred_element_type=jnp.float32,
    )                                                      # [L, pt, d]
    acc_ref[...] = (
        acc_ref[...] * corr[..., None] + jnp.transpose(pv, (1, 0, 2))
    )
    m_ref[...] = m_new[..., None]

    @pl.when(j == n_band - 1)
    def _done():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def banded_ragged_consensus(
    levels: jnp.ndarray,
    *,
    row_start: jnp.ndarray,
    row_len: jnp.ndarray,
    window: int,
    page_tokens: int,
    attend_self: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for banded_ragged_consensus_attention
    (serve/early_exit.py) running the streaming Pallas kernel on TPU (or
    anywhere under interpret=True); falls back to the jnp banded route
    otherwise, which keeps CPU serving on the bitwise contract."""
    from glom_tpu.serve.early_exit import banded_ragged_consensus_attention

    on_tpu = jax.devices()[0].platform == "tpu"
    if not (on_tpu or interpret):
        return banded_ragged_consensus_attention(
            levels, row_start=row_start, row_len=row_len, window=window,
            page_tokens=page_tokens, attend_self=attend_self,
        )
    T, L, d = levels.shape
    pt = page_tokens
    if T % pt or window % pt:
        raise ValueError(
            f"banded consensus needs page-aligned shapes: T={T}, "
            f"window={window}, page_tokens={pt}"
        )
    P = T // pt
    n_band = window // pt
    band_page0 = (row_start[::pt] // pt).astype(jnp.int32)  # [P]
    len_page = row_len[::pt].astype(jnp.int32)              # [P]
    pages = levels.reshape(P, pt, L, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(P, n_band),
        in_specs=[
            pl.BlockSpec((1, pt, L, d), lambda p, j, band, ln: (p, 0, 0, 0)),
            pl.BlockSpec(
                (1, pt, L, d),
                lambda p, j, band, ln: (
                    jnp.minimum(band[p] + j, P - 1), 0, 0, 0
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, pt, L, d), lambda p, j, band, ln: (p, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((pt, L, 1), jnp.float32),
            pltpu.VMEM((pt, L, 1), jnp.float32),
            pltpu.VMEM((pt, L, d), jnp.float32),
        ],
    )
    kernel = partial(
        _banded_kernel,
        pt=pt, n_band=n_band, attend_self=attend_self,
        scale=d ** -0.5,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, pt, L, d), levels.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(band_page0, len_page, pages, pages)
    return out.reshape(T, L, d)
