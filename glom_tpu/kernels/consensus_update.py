"""Pallas TPU kernel: blockwise consensus attention fused with the 4-way
mean column update.

Reference parity: ConsensusAttention.forward + the update mean
(glom_pytorch/glom_pytorch.py:54-71 and :124-140). One kernel program
computes, for one (level g, image b, row-tile i):

    cons = softmax_j( q_i . normalize(k)_j * d^-1/2  [dual masks] ) @ v
    out  = (levels_i + bottom_up_i + top_down_i + cons) / div_g

with a flash-style ONLINE softmax over j-tiles — the [n, n] similarity is
never materialized (O(n) memory in the patch axis), which is the
long-context path SURVEY.md §2.2 calls for. Both reference mask semantics
live in the inner loop:

  * attend_self=False: the DIAGONAL similarity is REPLACED by the soft
    -5e-4 penalty (reference TOKEN_ATTEND_SELF_VALUE, :9/:61-63);
  * local radius > 0: pairs farther than `radius` in Euclidean patch-grid
    distance are hard-masked to -3e38 (reference cdist buffer, :42-52).
    The mask is computed in-register from iota (no [n, n] HBM buffer at
    all — the reference's O(n^2) init-time cost disappears), and j-tiles
    that are ENTIRELY outside the radius band are skipped (block
    sparsity): rows i and j can only interact if their grid rows differ
    by <= radius, so the live j-window per i-tile is static arithmetic.

The epilogue folds in the per-level mean (4 contributions, 3 at the top
level — reference :121-122) and the zero top-down of the top level
(reference :130 F.pad) by masking the g = L-1 top-down tile, so XLA's
separate pad + add + divide HBM sweeps disappear.

Layout: level-major [L, B, n, d] ("lm") — the batched-matmul-natural
layout; glom_tpu.models.core keeps the scan carry in this layout so no
transposes appear between kernels.

Backward: custom_vjp over two more Pallas kernels (flash-attention-style).
The training forward additionally saves the per-row softmax statistics
(m, l) — two [L, B, n, 1] f32 outputs, the flash-attention logsumexp
residual trade — so NEITHER backward kernel re-derives them online:
p_ij = exp(s_ij - m_i) / l_i directly, which makes both passes pure
accumulations that stream k/v (resp. q/dcons) tiles through a WINDOWED
INNER GRID AXIS with f32 VMEM scratch accumulators. No full [n, d] row
ever sits resident in VMEM (the round-2 design's _BWD_ROW_LIMIT and its
dense fallback past n=4096 are gone — any n streams at O(n) memory,
double-buffered by the Mosaic pipeline).

The dq pass avoids needing D = rowsum(dcons . cons) up front via the
decomposition ds_ij = p_ij (dP_ij - D_i):

    dq_i = scale * (A_i - D_i * B_i),  A = sum_j (p*dP)~ @ k,
                                       B = sum_j p~ @ k,
                                       D = sum_j rowsum(p*dP)

(~ = diagonal zeroed when attend_self=False; D keeps the full sum) — one
j-sweep, 4 matmuls per tile, emitting D as a byproduct for the dkv pass.
The dkv pass accumulates dv_j and dk_j over the i-window, pushes dk
through the row-local k-normalization VJP, and its epilogue folds the
complete dlevels (dmean + dq + dv + dk-VJP) into one output write. Both
passes skip dead tiles under the local-radius band: the inner grid axis
is sized to the LIVE window (static arithmetic), with edge duplicates
masked by pl.when.

Dispatch: the dense-recompute VJP (one XLA fusion over the materialized
[n, n] similarity) beats the blockwise kernels where n is small or the
mask has no sparsity to skip — _fused_bwd picks by a measured crossover
on (n, radius); see _use_blockwise_bwd for the table.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE

_NEG_MAX = float(jnp.finfo(jnp.float32).min)

def _row_col(idx, side):
    """Patch-grid (row, col) coordinates of flat patch indices."""
    return idx // side, idx % side


def _apply_masks(s, row_ids, col_ids, *, side, radius, attend_self):
    """The dual mask semantics shared by EVERY kernel (reference :9/:61-67):
    diagonal REPLACED by the soft -5e-4 when attend_self=False; pairs past
    the Euclidean grid radius hard-masked to -3e38. row_ids/col_ids are
    QUERY-/KEY-index iotas shaped like s's trailing two dims."""
    if not attend_self:
        s = jnp.where((row_ids == col_ids)[None], TOKEN_ATTEND_SELF_VALUE, s)
    if radius > 0:
        ri, ci = _row_col(row_ids, side)
        rj, cj = _row_col(col_ids, side)
        dist2 = (ri - rj) ** 2 + (ci - cj) ** 2
        s = jnp.where(
            (dist2.astype(jnp.float32) > radius * radius)[None], _NEG_MAX, s
        )
    return s


def _norm_vjp(dk, x):
    """VJP of the row-local k-normalization k = x / max(||x||, eps)
    (helpers.l2norm), shared by every backward kernel. dk f32, x compute
    dtype; returns f32."""
    f32 = jnp.float32
    x32 = x.astype(f32)
    r = jnp.sqrt(jnp.sum(x32 * x32, axis=-1, keepdims=True))
    inv = 1.0 / jnp.maximum(r, 1e-12)
    a = jnp.sum(dk * x32, axis=-1, keepdims=True)
    return dk * inv - jnp.where(r >= 1e-12, a * x32 * inv * inv / r, 0.0)


def _consensus_update_kernel(
    x_ref,      # [1, TB, TI, d] levels q/self tile
    kv_ref,     # [1, TB, n, d]  full rows of levels for (g, b-tile): k and v
    bu_ref,     # [1, TB, TI, d] bottom-up contribution tile
    td_ref,     # [1, TB, TI, d] top-down tile (index-clamped at the top level)
    out_ref,    # [1, TB, TI, d]
    *stats_refs,  # training fwd: m_ref, l_ref [1, TB, TI, 1] f32 — the
                #   flash-style softmax residuals the backward kernels
                #   consume instead of recomputing the row statistics
    levels_count: int,
    side: int,
    radius: float,
    attend_self: bool,
    tile_i: int,
    tile_j: int,
    n: int,
):
    """One program: a (level g, image-tile, row-tile i) block. The TB images
    ride the batch dimension of a single batched dot_general per j-step, so
    small-n configs still feed the MXU one large op instead of TB tiny ones.
    """
    g = pl.program_id(0)
    i = pl.program_id(2)
    tb = x_ref.shape[1]
    d = x_ref.shape[-1]
    scale = d ** -0.5

    x = x_ref[0]  # [TB, TI, d]
    q32 = x.astype(jnp.float32)

    row_ids = i * tile_i + jax.lax.broadcasted_iota(jnp.int32, (tile_i, tile_j), 0)

    # Block sparsity for the local mask: the live j-window for this i-tile
    # (i is traced, so the window is int32 arithmetic; fori_loop takes
    # dynamic bounds). Shared with both backward kernels via _window.
    j_lo, j_hi = _window(i * tile_i, tile_i, tile_j, n // tile_j, side, radius)

    m0 = jnp.full((tb, tile_i, 1), _NEG_MAX, jnp.float32)
    l0 = jnp.zeros((tb, tile_i, 1), jnp.float32)
    acc0 = jnp.zeros((tb, tile_i, d), jnp.float32)

    def j_body(j, carry):
        m, l, acc = carry
        kv = kv_ref[0, :, pl.ds(j * tile_j, tile_j), :]  # [TB, TJ, d]
        # k-only L2 normalization (reference :56): v stays raw.
        k = _normalized_k(kv)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [TB, TI, TJ]

        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        s = _apply_masks(
            s, row_ids, col_ids,
            side=side, radius=radius, attend_self=attend_self,
        )

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # Downcast the probabilities for the MXU, matching the dense op's
        # softmax(...).astype(levels.dtype) before attn @ v.
        pv = jax.lax.dot_general(
            p.astype(x.dtype), kv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(j_lo, j_hi, j_body, (m0, l0, acc0))
    cons = acc / l
    if stats_refs:
        m_ref, l_ref = stats_refs[:2]
        m_ref[0] = m
        l_ref[0] = l
        if len(stats_refs) == 3:
            # cons residual for the one-sweep long-row backward (it makes
            # D_i = rowsum(dcons_i * cons_i) row-local there)
            stats_refs[2][0] = cons.astype(stats_refs[2].dtype)

    bu = bu_ref[0].astype(jnp.float32)
    td = td_ref[0].astype(jnp.float32)
    # Top level: no top-down contribution (its tile is index-clamped junk)
    # and a 3-way divisor (reference :121-122, :130).
    is_top = g == levels_count - 1
    td = jnp.where(is_top, 0.0, td)
    div = jnp.where(is_top, 3.0, 4.0)
    new = (q32 + bu + td + cons) / div
    out_ref[0] = new.astype(out_ref.dtype)


def _consensus_update_kernel_streamed(
    x_ref,      # [1, TB, TI, d] levels q/self tile (resident across jw)
    kv_ref,     # [1, TB, TJ, d] STREAMED levels j-tile
    bu_ref,     # [1, TB, TI, d] (resident; epilogue)
    td_ref,     # [1, TB, TI, d] (resident, index-clamped at the top level)
    out_ref,    # [1, TB, TI, d] written at the last jw step
    *stats_refs,  # optional m_ref, l_ref [1, TB, TI, 1] f32 outs
    levels_count: int,
    side: int,
    radius: float,
    attend_self: bool,
    tile_i: int,
    tile_j: int,
    n: int,
):
    """Large-n forward: the same online softmax as _consensus_update_kernel
    but with the j sweep as a STREAMED inner grid axis (windowed under the
    local-radius band) and the (m, l, acc) carry in VMEM scratch — no full
    [n, d] k/v row residency, O(n) VMEM at any n. Dispatched by _forward
    when the resident-row working set would overflow the scoped-VMEM
    budget (measured: bf16 n=9216 needs 47MB > the 43MB scope with the
    resident-row kernel)."""
    m_acc, l_acc, acc_acc = stats_refs[-3:]
    out_stats = stats_refs[:-3]
    g = pl.program_id(0)
    i = pl.program_id(2)
    jw = pl.program_id(3)
    num_jw = pl.num_programs(3)
    d = x_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32
    n_tj = n // tile_j

    @pl.when(jw == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, _NEG_MAX)
        l_acc[...] = jnp.zeros_like(l_acc)
        acc_acc[...] = jnp.zeros_like(acc_acc)

    lo = _win_lo_tile(i, tile_i, tile_j, side, radius)
    hi = _win_hi_tile(i, tile_i, tile_j, n_tj, side, radius)
    j = lo + jw

    @pl.when(j < hi)
    def _step():
        x = x_ref[0]
        kv = kv_ref[0]
        k = _normalized_k(kv)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )
        row_ids = i * tile_i + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 0
        )
        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        s = _apply_masks(
            s, row_ids, col_ids,
            side=side, radius=radius, attend_self=attend_self,
        )
        m = m_acc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_acc[...] = l_acc[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(x.dtype), kv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        acc_acc[...] = acc_acc[...] * corr + pv
        m_acc[...] = m_new

    @pl.when(jw == num_jw - 1)
    def _final():
        m = m_acc[...]
        l = l_acc[...]
        cons = acc_acc[...] / l
        if out_stats:
            out_stats[0][0] = m
            out_stats[1][0] = l
            if len(out_stats) == 3:
                out_stats[2][0] = cons.astype(out_stats[2].dtype)
        bu = bu_ref[0].astype(f32)
        td = td_ref[0].astype(f32)
        is_top = g == levels_count - 1
        td = jnp.where(is_top, 0.0, td)
        div = jnp.where(is_top, 3.0, 4.0)
        out_ref[0] = ((x_ref[0].astype(f32) + bu + td + cons) / div).astype(
            out_ref.dtype
        )


# Resident-row cap for the FORWARD kernel: beyond this the [TB, n, d] k/v
# block (double-buffered by the pipeline) pushes the scoped-VMEM working
# set over Mosaic's budget and the streamed-forward variant dispatches.
_FWD_ROW_LIMIT = 4 * 1024 * 1024

# Largest n the single-tile fused backward handles (whole row as one block;
# sim + tiles stay within the VMEM budget at d<=1024).
_SMALL_BWD_N = 512


def _pick_tile(n: int, cap: int = 256) -> int:
    for t in (512, 256, 128, 64, 32, 16, 8):
        if t <= cap and n % t == 0 and t <= n:
            return t
    return n


# Shared per-program VMEM budget for picking the batch tile (the kernels'
# scoped limits are higher; this leaves pipelining headroom).
_TILE_B_BUDGET = 12 * 1024 * 1024


def _fit_tile_b(B: int, ws_of_tb) -> int:
    """Largest batch tile in (8, 4, 2, 1) dividing B whose working set
    (bytes, per ws_of_tb(tb)) fits _TILE_B_BUDGET. The single source of
    the candidate ladder + budget for all three consensus kernels."""
    for tb in (8, 4, 2, 1):
        if B % tb == 0 and ws_of_tb(tb) <= _TILE_B_BUDGET:
            return tb
    return 1


def _pick_tile_b(
    B: int, n: int, d: int, tile_i: int, tile_j: int, itemsize: int,
    *, streamed: bool = False,
) -> int:
    """Batch tile for the FORWARD: ~2x-buffered in/out blocks + f32
    accumulators + the sim tile. The streamed layout replaces the resident
    k/v rows with one 2x-buffered j-tile + the f32 (m, l, acc) scratch."""

    def ws(tb):
        blocks = 5 * tb * tile_i * d * itemsize * 2  # x/bu/td/out/kv, 2x buffered
        if streamed:
            kv_extra = tb * tile_j * d * itemsize * 2
        else:
            kv_extra = tb * (n - tile_i) * d * itemsize * 2 if n > tile_i else 0
        scratch = tb * tile_i * (d + 1) * 4 * 2 + tb * tile_i * tile_j * 4
        return blocks + kv_extra + scratch

    return _fit_tile_b(B, ws)


def _forward(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool,
    save_stats: bool = False,
    save_cons: bool = False,
):
    """save_stats=True (the training forward under custom_vjp) also emits
    the f32 row statistics (m, l) consumed by the backward kernels.
    save_cons=True additionally emits the attention output `cons` (compute
    dtype) — the residual that lets the ONE-SWEEP long-row backward
    compute D_i = rowsum(dcons_i * cons_i) row-locally instead of needing
    a separate D-producing pass.

    Two grid layouts behind one contract: resident-row (k/v rows live in
    VMEM, fori_loop over j — fastest when they fit) vs streamed (j as a
    windowed inner grid axis, (m, l, acc) in scratch — O(n) VMEM at any
    n); dispatched on _FWD_ROW_LIMIT."""
    L, B, n, d = levels_lm.shape
    tile_i = _pick_tile(n)
    # Global consensus: a wider j-tile halves the online-softmax correction
    # steps (measured 1.91 -> 1.69 ms at n=4096, beating the dense XLA
    # path). Local radius: keep j-tiles at 256 so the block-sparse window
    # stays fine-grained (a 512 tile erases the skip at side<=32).
    tile_j = _pick_tile(n, cap=512 if radius <= 0 else 256)
    streamed = n * d * levels_lm.dtype.itemsize > _FWD_ROW_LIMIT
    tile_b = _pick_tile_b(
        B, n, d, tile_i, tile_j, levels_lm.dtype.itemsize, streamed=streamed
    )

    kw = dict(
        levels_count=L,
        side=side,
        radius=float(radius),
        attend_self=attend_self,
        tile_i=tile_i,
        tile_j=tile_j,
        n=n,
    )
    out_shape = jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype)
    if streamed:
        def i_spec(last):
            return pl.BlockSpec(
                (1, tile_b, tile_i, last), lambda g, b, i, jw: (g, b, i, 0)
            )

        n_tj = n // tile_j

        def kv_map(g, b, i, jw, _tj=n_tj):
            lo = _win_lo_tile(i, tile_i, tile_j, side, radius)
            return (g, b, jnp.minimum(lo + jw, _tj - 1), 0)

        out_spec = i_spec(d)
        if save_stats:
            stat_shape = jax.ShapeDtypeStruct((L, B, n, 1), jnp.float32)
            out_shape = (out_shape, stat_shape, stat_shape)
            out_spec = (out_spec, i_spec(1), i_spec(1))
            if save_cons:
                out_shape = out_shape + (
                    jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
                )
                out_spec = out_spec + (i_spec(d),)
        f32 = jnp.float32
        return pl.pallas_call(
            partial(_consensus_update_kernel_streamed, **kw),
            out_shape=out_shape,
            grid=(
                L, B // tile_b, n // tile_i,
                _win_len(tile_i, tile_j, n_tj, side, radius),
            ),
            in_specs=[
                i_spec(d),  # x
                pl.BlockSpec((1, tile_b, tile_j, d), kv_map),  # streamed kv
                i_spec(d),  # bu
                pl.BlockSpec(
                    (1, tile_b, tile_i, d),
                    lambda g, b, i, jw, _L=L: (jnp.minimum(g, _L - 2), b, i, 0),
                ),  # td (clamped top)
            ],
            out_specs=out_spec,
            scratch_shapes=[
                pltpu.VMEM((tile_b, tile_i, 1), f32),  # m
                pltpu.VMEM((tile_b, tile_i, 1), f32),  # l
                pltpu.VMEM((tile_b, tile_i, d), f32),  # acc
            ],
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=32 * 1024 * 1024
            ),
            interpret=interpret,
        )(levels_lm, levels_lm, bu_lm, td_lm)

    grid = (L, B // tile_b, n // tile_i)
    out_spec = pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0))
    if save_stats:
        stat_shape = jax.ShapeDtypeStruct((L, B, n, 1), jnp.float32)
        stat_spec = pl.BlockSpec((1, tile_b, tile_i, 1), lambda g, b, i: (g, b, i, 0))
        out_shape = (out_shape, stat_shape, stat_shape)
        out_spec = (out_spec, stat_spec, stat_spec)
        if save_cons:
            out_shape = out_shape + (
                jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
            )
            out_spec = out_spec + (
                pl.BlockSpec(
                    (1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)
                ),
            )
    return pl.pallas_call(
        partial(_consensus_update_kernel, **kw),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # x
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, i: (g, b, 0, 0)),  # kv
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # bu
            # td has L-1 groups; clamp the top level's index (masked in-kernel)
            pl.BlockSpec(
                (1, tile_b, tile_i, d),
                lambda g, b, i, _L=L: (jnp.minimum(g, _L - 2), b, i, 0),
            ),
        ],
        out_specs=out_spec,
        # The cons residual output adds a 2x-buffered [TB, TI, d] block the
        # default 16MB scope doesn't fit at resident-row n=1024 (measured
        # 68K over); v5e has 128MB physical.
        compiler_params=(
            pltpu.CompilerParams(vmem_limit_bytes=32 * 1024 * 1024)
            if save_cons
            else None
        ),
        interpret=interpret,
    )(levels_lm, levels_lm, bu_lm, td_lm)


def _normalized_k(kv_tile):
    """k-only L2 normalization in f32, downcast to the compute dtype
    (reference :56 / helpers.l2norm: x / max(||x||, 1e-12))."""
    kv32 = kv_tile.astype(jnp.float32)
    norm = jnp.sqrt(jnp.sum(kv32 * kv32, axis=-1, keepdims=True))
    return (kv32 / jnp.maximum(norm, 1e-12)).astype(kv_tile.dtype)


def _window(center_lo, extent, tile, n_tiles, side, radius):
    """Live tile-window [lo, hi) along the opposite attention axis: flat
    indices interact only when their grid rows differ by <= radius, i.e.
    they are within (radius + 1) * side flat positions."""
    if radius <= 0:
        return 0, n_tiles
    reach = int(radius + 1) * side
    lo = center_lo - reach
    hi = center_lo + extent + reach
    return jnp.maximum(lo // tile, 0), jnp.minimum(-(-hi // tile), n_tiles)


def _win_lo_tile(t, tile_self, tile_other, side, radius):
    """First live tile index on the opposite attention axis for tile `t`
    (traced int32): flat indices interact only within (radius+1)*side."""
    if radius <= 0:
        return jnp.int32(0)
    reach = int(radius + 1) * side
    return jnp.maximum((t * tile_self - reach) // tile_other, 0)


def _win_hi_tile(t, tile_self, tile_other, n_tiles, side, radius):
    """One-past-last live tile index (traced int32)."""
    if radius <= 0:
        return jnp.int32(n_tiles)
    reach = int(radius + 1) * side
    return jnp.minimum(-(-(t * tile_self + tile_self + reach) // tile_other), n_tiles)


def _win_len(tile_self, tile_other, n_tiles, side, radius) -> int:
    """STATIC upper bound on live tiles per window — the size of the inner
    streaming grid axis. Edge tiles whose (lo + w) lands past hi are DMA'd
    clamped and masked off with pl.when."""
    if radius <= 0:
        return n_tiles
    reach = int(radius + 1) * side
    return min(n_tiles, (tile_self + 2 * reach) // tile_other + 2)


def _consensus_bwd_dq_kernel(
    x_ref,      # [1, TB, TI, d]  levels q tile (resident across jw)
    kv_ref,     # [1, TB, TJ, d]  STREAMED levels j-tile (k_j and v_j)
    dm_ref,     # [1, TB, TI, d]  RAW output-cotangent tile (compute dtype;
                #                 the 4-vs-3 mean divisor is applied HERE,
                #                 from the level grid index — feeding the
                #                 kernel g directly avoids a separate
                #                 divide+downcast HBM sweep in the caller)
    m_ref,      # [1, TB, TI, 1]  f32 row max SAVED BY THE FORWARD
    l_ref,      # [1, TB, TI, 1]  f32 row softmax denominator (forward)
    dq_ref,     # [1, TB, TI, d]  f32 out (written at the last jw step)
    dd_ref,     # [1, TB, TI, 1]  f32 out: D_i = sum_j p_ij dP_ij,
                #                 consumed by the dkv pass
    a_acc,      # VMEM scratch [TB, TI, d] f32: sum_j (p*dP)~ @ k
    b_acc,      # VMEM scratch [TB, TI, d] f32: sum_j p~ @ k
    d_acc,      # VMEM scratch [TB, TI, 1] f32: running D
    *, side, radius, attend_self, tile_i, tile_j, n,
):
    """Pass 1 of the blockwise consensus backward: ONE streamed j-sweep.
    With (m, l) saved by the forward, p_ij = exp(s_ij - m_i)/l_i directly,
    and the D-before-ds ordering problem dissolves via

        dq_i = scale * (A_i - D_i B_i),
        A = sum_j (p*dP)~ @ k,  B = sum_j p~ @ k,  D = sum_j rowsum(p*dP)

    (~ = diagonal zeroed when attend_self=False — the diagonal score was
    REPLACED by a constant so no grad flows through it; D keeps the FULL
    sum, since D_i = rowsum(dcons_i * cons_i) includes the diagonal's v).
    The inner grid axis jw walks the live j-window (block sparsity under
    the local-radius band is grid-level: dead tiles are never DMA'd);
    accumulators persist in VMEM scratch across jw."""
    i = pl.program_id(2)
    jw = pl.program_id(3)
    num_jw = pl.num_programs(3)
    # dcons = g / div: top level (last grid-0 index) averages 3. program_id
    # must be read at kernel top level — inside a pl.when branch (a
    # lax.cond) the interpret-mode substitution misses it.
    div = jnp.where(pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0)
    d = x_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32
    n_tj = n // tile_j

    @pl.when(jw == 0)
    def _init():
        a_acc[...] = jnp.zeros_like(a_acc)
        b_acc[...] = jnp.zeros_like(b_acc)
        d_acc[...] = jnp.zeros_like(d_acc)

    lo = _win_lo_tile(i, tile_i, tile_j, side, radius)
    hi = _win_hi_tile(i, tile_i, tile_j, n_tj, side, radius)
    j = lo + jw

    @pl.when(j < hi)
    def _step():
        x = x_ref[0]
        dcons = dm_ref[0].astype(f32) / div
        m = m_ref[0]
        l = l_ref[0]
        kv = kv_ref[0]
        k = _normalized_k(kv)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )
        row_ids = i * tile_i + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 0
        )
        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        s = _apply_masks(
            s, row_ids, col_ids,
            side=side, radius=radius, attend_self=attend_self,
        )
        p = jnp.exp(s - m) / l  # [TB, TI, TJ] f32
        dp = jax.lax.dot_general(
            dcons.astype(x.dtype), kv, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=f32,
        )  # dP_ij = dcons_i . v_j
        t = p * dp
        d_acc[...] += jnp.sum(t, axis=-1, keepdims=True)
        if not attend_self:
            diag = (row_ids == col_ids)[None]
            t = jnp.where(diag, 0.0, t)
            p = jnp.where(diag, 0.0, p)
        a_acc[...] += jax.lax.dot_general(
            t.astype(x.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        b_acc[...] += jax.lax.dot_general(
            p.astype(x.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )

    @pl.when(jw == num_jw - 1)
    def _final():
        dd = d_acc[...]
        dq_ref[0] = (a_acc[...] - dd * b_acc[...]) * scale
        dd_ref[0] = dd


def _consensus_bwd_small_kernel(
    x_ref,      # [1, TB, n, d]  levels (q = k-source = v), whole row
    dm_ref,     # [1, TB, n, d]  RAW output cotangent (compute dtype)
    m_ref,      # [1, TB, n, 1]  f32 forward stats
    l_ref,      # [1, TB, n, 1]
    dlv_ref,    # [1, TB, n, d]  COMPLETE dlevels (levels dtype)
    dmean_ref,  # [1, TB, n, d]  g/div downcast — the d(bu) cotangent
                #                (d(td) is its [:L-1] slice), emitted here
                #                so the caller's divide+downcast sweep of g
                #                disappears
    *, side, radius, attend_self, n,
):
    """Single-tile consensus backward: when the whole patch row fits one
    tile (n <= 512 — the flagship n=256 lives here), the i- and j-ranges
    coincide, so ONE program computes the scores ONCE and emits the
    complete dlevels: 5 matmuls (s, dP, dq, dv, dk) vs the 8 of the
    two-pass form, ONE exp, and — the dominant saving at train shapes —
    no [L, B, n, d] f32 dq / [L, B, n, 1] stats round-tripping through
    HBM between passes (~200 MB per scan iteration at the flagship).
    With dd known in-register the ds = p*(dP - dd) form needs no A/B
    decomposition."""
    f32 = jnp.float32
    div = jnp.where(pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0)
    x = x_ref[0]              # [TB, n, d]
    dcons = dm_ref[0].astype(f32) / div
    dlv = _small_bwd_math(
        x, dcons, m_ref[0], l_ref[0],
        side=side, radius=radius, attend_self=attend_self, n=n,
    )
    dlv_ref[0] = dlv.astype(dlv_ref.dtype)
    dmean_ref[0] = dcons.astype(dmean_ref.dtype)


def _small_bwd_math(x, dcons, m, l, *, side, radius, attend_self, n):
    """The single-tile backward's math, shared with the hand-rolled loop
    VJP's combine kernel (kernels/fused_loop.py): given the whole patch row
    x [TB, n, d] and the DIVIDED f32 output cotangent dcons, return the
    complete f32 d(levels) = dcons + dq + dv + norm-VJP(dk)."""
    f32 = jnp.float32
    d = x.shape[-1]
    scale = d ** -0.5
    k = _normalized_k(x)

    s = (
        jax.lax.dot_general(
            x, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=f32
        )
        * scale
    )  # [TB, n, n]
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    diag = (row_ids == col_ids)[None]
    s = _apply_masks(
        s, row_ids, col_ids, side=side, radius=radius, attend_self=attend_self
    )

    p = jnp.exp(s - m) / l  # [TB, n(i), n(j)] f32
    dp = jax.lax.dot_general(
        dcons.astype(x.dtype), x, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=f32,
    )  # dP_ij = dcons_i . v_j
    dd = jnp.sum(p * dp, axis=-1, keepdims=True)  # FULL sum incl. diagonal
    ds = p * (dp - dd)
    if not attend_self:
        ds = jnp.where(diag, 0.0, ds)
    dsc = ds.astype(x.dtype)

    # dq_i = scale * sum_j ds_ij k_j
    dq = jax.lax.dot_general(
        dsc, k, (((2,), (1,)), ((0,), (0,))), preferred_element_type=f32
    ) * scale
    # dv_j = sum_i p_ij dcons_i  (UNMASKED p: the diagonal feeds v)
    dv = jax.lax.dot_general(
        p.astype(x.dtype), dcons.astype(x.dtype), (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=f32,
    )
    # dk_j = scale * sum_i ds_ij q_i
    dk = jax.lax.dot_general(
        dsc, x, (((1,), (1,)), ((0,), (0,))), preferred_element_type=f32
    ) * scale

    dxn = _norm_vjp(dk, x)
    return dcons + dq + dv + dxn


def _consensus_bwd_dkv_kernel(
    xj_ref,     # [1, TB, TJ, d]  levels j-tile (k_j, v_j; resident)
    gj_ref,     # [1, TB, TJ, d]  RAW cotangent j-tile (resident; epilogue)
    dqj_ref,    # [1, TB, TJ, d]  f32 dq tile from pass 1 (resident; epilogue)
    q_ref,      # [1, TB, TI, d]  STREAMED levels i-tile (queries)
    dm_ref,     # [1, TB, TI, d]  STREAMED raw cotangent i-tile (the mean
                #                 divisor is applied here, as in the dq pass)
    m_ref,      # [1, TB, TI, 1]  STREAMED f32 stats (forward / dq pass)
    l_ref,      # [1, TB, TI, 1]
    dd_ref,     # [1, TB, TI, 1]
    out_ref,    # [1, TB, TJ, d]  levels dtype: the COMPLETE dlevels tile
                #                 (dmean + dq + dv + normalizeVJP(dk)) —
                #                 folding the sum here removes the separate
                #                 XLA add/convert HBM sweeps
    dv_acc,     # VMEM scratch [TB, TJ, d] f32
    dk_acc,     # VMEM scratch [TB, TJ, d] f32
    *, side, radius, attend_self, tile_i, tile_j, n,
):
    """Pass 2: for each j-tile, stream the live i-window (inner grid axis
    iw) and accumulate dv_j = sum_i p_ij dcons_i and
    dk_j = scale * sum_i ds_ij q_i in VMEM scratch; the last iw step pushes
    dk through the row-local k-normalization VJP and finishes dlevels:
    out_j = g_j/div + dq_j + dv_j + dxn_j, downcast once."""
    j = pl.program_id(2)
    iw = pl.program_id(3)
    num_iw = pl.num_programs(3)
    # program_id reads must stay at kernel top level (see the dq kernel).
    inv_div = 1.0 / jnp.where(
        pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0
    )
    d = xj_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32
    n_ti = n // tile_i

    @pl.when(iw == 0)
    def _init():
        dv_acc[...] = jnp.zeros_like(dv_acc)
        dk_acc[...] = jnp.zeros_like(dk_acc)

    lo = _win_lo_tile(j, tile_j, tile_i, side, radius)
    hi = _win_hi_tile(j, tile_j, tile_i, n_ti, side, radius)
    i = lo + iw

    # g / div applied via the LINEAR uses of dcons: dv and dP are both
    # linear in dcons, so the divide moves onto the accumulated dots.
    xj = xj_ref[0]            # [TB, TJ, d] raw levels (v_j; k_j after norm)

    @pl.when(i < hi)
    def _step():
        k = _normalized_k(xj)
        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_j, tile_i), 0
        )
        q = q_ref[0]              # [TB, TI, d]
        dcons = dm_ref[0]         # [TB, TI, d] raw
        m = m_ref[0][..., 0]      # [TB, TI]
        l = l_ref[0][..., 0]
        dd = dd_ref[0][..., 0]

        # s2[b, tj, ti] = s[i, j] transposed
        s2 = (
            jax.lax.dot_general(
                k, q, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )  # [TB, TJ, TI]
        row_ids = i * tile_i + jax.lax.broadcasted_iota(
            jnp.int32, (tile_j, tile_i), 1
        )  # query index along the LAST axis here (both masks are symmetric
        #    in the pair, so the transposed orientation reuses the helper)
        s2 = _apply_masks(
            s2, col_ids, row_ids,
            side=side, radius=radius, attend_self=attend_self,
        )

        p2 = jnp.exp(s2 - m[:, None, :]) / l[:, None, :]     # [TB, TJ, TI]
        p2c = p2.astype(xj.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p2c, dcons, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        dp2 = (
            jax.lax.dot_general(
                xj, dcons, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * inv_div
        )  # dP2[b, tj, ti] = v_j . (dcons_i / div_i); dd is already divided
        ds2 = p2 * (dp2 - dd[:, None, :])
        if not attend_self:
            ds2 = jnp.where((col_ids == row_ids)[None], 0.0, ds2)
        dk_acc[...] += jax.lax.dot_general(
            ds2.astype(xj.dtype), q, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )

    @pl.when(iw == num_iw - 1)
    def _final():
        dv = dv_acc[...] * inv_div  # accumulated against the RAW cotangents
        dk = dk_acc[...] * scale
        dxn = _norm_vjp(dk, xj)
        # Epilogue: complete dlevels for this j-tile. dmean_j = g_j / div.
        gj = gj_ref[0].astype(f32) * inv_div
        out_ref[0] = (gj + dqj_ref[0] + dv + dxn).astype(out_ref.dtype)


def _consensus_bwd_onesweep_kernel(
    xj_ref,     # [1, TB, TJ, d]  levels j-tile (k_j and v_j; resident)
    gj_ref,     # [1, TB, TJ, d]  RAW cotangent j-tile (resident; epilogue)
    q_ref,      # [1, TB, TI, d]  STREAMED levels i-tile (queries)
    dm_ref,     # [1, TB, TI, d]  STREAMED raw cotangent i-tile
    cons_ref,   # [1, TB, TI, d]  STREAMED attention output SAVED by the
                #                 forward: D_i = rowsum(dcons_i * cons_i)
                #                 becomes row-LOCAL, which is what lets dq
                #                 and dkv share one sweep (the two-pass
                #                 design existed only because D had to be
                #                 produced before ds could be formed)
    m_ref,      # [1, TB, TI, 1]  f32 forward stats
    l_ref,      # [1, TB, TI, 1]
    out_ref,    # [1, TB, TJ, d]  PARTIAL dlevels j-tile: dmean + dv + dk-VJP
                #                 (dq joins in XLA — its rows finish only at
                #                 the end of the whole (g, b) subgrid)
    dq_ref,     # [1, TB, n, d]   f32 dq accumulator, RESIDENT across the
                #                 entire (j, iw) subgrid (constant index)
    dv_acc,     # VMEM scratch [TB, TJ, d] f32
    dk_acc,     # VMEM scratch [TB, TJ, d] f32
    *, side, radius, attend_self, tile_i, tile_j, n,
):
    """ONE-sweep blockwise consensus backward for long rows: for each
    j-tile, stream the live i-window once, computing the scores ONCE per
    (i, j) pair and accumulating ALL of dv_j, dk_j (VMEM scratch) and
    dq_i (a whole-row resident f32 block, row-sliced stores) — 5 matmuls
    per pair vs the two-pass form's 8 (which computed s and dP twice and
    round-tripped dq/D through HBM between passes)."""
    j = pl.program_id(2)
    iw = pl.program_id(3)
    num_iw = pl.num_programs(3)
    first = (j == 0) & (iw == 0)
    inv_div = 1.0 / jnp.where(
        pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0
    )
    d = xj_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32
    n_ti = n // tile_i

    @pl.when(first)
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(iw == 0)
    def _init():
        dv_acc[...] = jnp.zeros_like(dv_acc)
        dk_acc[...] = jnp.zeros_like(dk_acc)

    lo = _win_lo_tile(j, tile_j, tile_i, side, radius)
    hi = _win_hi_tile(j, tile_j, tile_i, n_ti, side, radius)
    i = lo + iw

    xj = xj_ref[0]            # [TB, TJ, d]

    @pl.when(i < hi)
    def _step():
        k = _normalized_k(xj)
        q = q_ref[0]              # [TB, TI, d]
        dcons = dm_ref[0].astype(f32) * inv_div
        dd = jnp.sum(dcons * cons_ref[0].astype(f32), axis=-1)  # [TB, TI]
        m = m_ref[0][..., 0]
        l = l_ref[0][..., 0]

        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_j, tile_i), 0
        )
        row_ids = i * tile_i + jax.lax.broadcasted_iota(
            jnp.int32, (tile_j, tile_i), 1
        )
        s2 = (
            jax.lax.dot_general(
                k, q, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )  # [TB, TJ, TI] — s transposed; masks are pair-symmetric
        s2 = _apply_masks(
            s2, col_ids, row_ids,
            side=side, radius=radius, attend_self=attend_self,
        )
        p2 = jnp.exp(s2 - m[:, None, :]) / l[:, None, :]
        dconsc = dcons.astype(xj.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p2.astype(xj.dtype), dconsc, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        dp2 = jax.lax.dot_general(
            xj, dconsc, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=f32,
        )  # dP2[b, tj, ti] = v_j . dcons_i
        ds2 = p2 * (dp2 - dd[:, None, :])
        if not attend_self:
            ds2 = jnp.where((col_ids == row_ids)[None], 0.0, ds2)
        ds2c = ds2.astype(xj.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds2c, q, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        # dq_i += scale * sum_j ds_ij k_j  (contract TJ); row-sliced store
        # into the resident whole-row accumulator.
        dq_step = jax.lax.dot_general(
            ds2c, k, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        ) * scale  # [TB, TI, d]
        dq_ref[0, :, pl.ds(i * tile_i, tile_i), :] += dq_step

    @pl.when(iw == num_iw - 1)
    def _final():
        dk = dk_acc[...] * scale
        dxn = _norm_vjp(dk, xj)
        gj = gj_ref[0].astype(f32) * inv_div
        out_ref[0] = (gj + dv_acc[...] + dxn).astype(out_ref.dtype)


def _onesweep_ws(tb: int, n: int, d: int, tile: int, itemsize: int) -> int:
    """One-sweep working set: the whole-row resident f32 dq block + resident
    xj/gj + 2x-buffered streamed tiles + f32 scratch + sim tiles + out."""
    dq = tb * n * d * 4
    resident = 2 * tb * tile * d * itemsize * 2
    streamed = 3 * tb * tile * d * itemsize * 2 + 2 * tb * tile * 4 * 2
    scratch = 2 * tb * tile * d * 4
    sim = 3 * tb * tile * tile * 4
    out = tb * tile * d * itemsize * 2
    return dq + resident + streamed + scratch + sim + out


_ONESWEEP_BUDGET = 48 * 1024 * 1024


def _onesweep_ok(B: int, n: int, d: int, itemsize: int) -> bool:
    """Eligibility of the one-sweep backward: its whole-row f32 dq
    accumulator must fit VMEM alongside the tiles even at batch tile 1."""
    return _onesweep_ws(1, n, d, _pick_tile(n), itemsize) <= _ONESWEEP_BUDGET


def _consensus_bwd_onesweep(
    levels_lm, graw, m, l, cons, *, side, radius, attend_self, interpret
):
    L, B, n, d = levels_lm.shape
    tile = _pick_tile(n)
    itemsize = levels_lm.dtype.itemsize
    tile_b = _fit_tile_b(B, lambda tb: _onesweep_ws(tb, n, d, tile, itemsize))
    f32 = jnp.float32
    n_t = n // tile

    def _j_spec(last):
        return pl.BlockSpec(
            (1, tile_b, tile, last), lambda g, b, j, iw: (g, b, j, 0)
        )

    def _i_map(g, b, j, iw, _ti=n_t):
        lo = _win_lo_tile(j, tile, tile, side, radius)
        return (g, b, jnp.minimum(lo + iw, _ti - 1), 0)

    def _i_spec(last):
        return pl.BlockSpec((1, tile_b, tile, last), _i_map)

    iw_len = _win_len(tile, tile, n_t, side, radius)
    out, dq = pl.pallas_call(
        partial(
            _consensus_bwd_onesweep_kernel,
            side=side, radius=float(radius), attend_self=attend_self,
            tile_i=tile, tile_j=tile, n=n,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
            jax.ShapeDtypeStruct((L, B, n, d), f32),
        ),
        grid=(L, B // tile_b, n_t, iw_len),
        in_specs=[
            _j_spec(d),   # xj (resident)
            _j_spec(d),   # gj (resident, epilogue)
            _i_spec(d),   # streamed q
            _i_spec(d),   # streamed raw cotangent
            _i_spec(d),   # streamed cons residual
            _i_spec(1),   # m
            _i_spec(1),   # l
        ],
        out_specs=(
            _j_spec(d),
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, j, iw: (g, b, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((tile_b, tile, d), f32),
            pltpu.VMEM((tile_b, tile, d), f32),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(levels_lm, graw, levels_lm, graw, cons, m, l)
    # dq rows complete only at the end of each (g, b) subgrid — joined here
    # (one fused add sweep, O(n*d), vs the O(n^2) kernel work).
    return (out.astype(f32) + dq).astype(levels_lm.dtype)


def _pick_tile_b_bwd(B: int, n: int, d: int, tile: int, itemsize: int) -> int:
    """Batch tile for the BACKWARD kernels. Nothing full-row is resident
    any more (the i/j windows stream through the inner grid axis); the
    working set is resident tiles (x/dm or xj/gj/dqj), one streamed tile
    pair 2x-buffered, the f32 scratch accumulators, and the out block."""

    def ws(tb):
        resident = tb * tile * d * (2 * itemsize + 4)      # x/dm + f32 dqj
        streamed = 2 * tb * tile * d * (itemsize + itemsize)  # q + dm tiles
        scratch = 2 * tb * tile * d * 4 + tb * tile * 4    # A/B (or dv/dk) + D
        sim = 2 * tb * tile * tile * 4                     # p / dp tiles
        out = tb * tile * d * (4 + itemsize)
        return resident + streamed + scratch + sim + out

    return _fit_tile_b(B, ws)


def _consensus_update_bwd(
    levels_lm, g, m, l, cons=None, *, side, radius, attend_self, interpret
):
    """Blockwise backward for the fused consensus+update: returns the
    COMPLETE d(levels) = dmean + dq + (dv + dk-through-normalization), in
    the levels dtype. `g` is the RAW output cotangent in the compute dtype
    — the 4-vs-3 mean divisor is applied inside the kernels from the level
    grid index, and the dkv pass's epilogue folds dmean + dq into its
    output, so neither a divided copy of g nor the f32 partial sums ever
    make a separate HBM round trip. (m, l) are the forward's saved row
    statistics; both passes stream their opposite-axis tiles through a
    windowed inner grid axis — O(n) VMEM at ANY n.

    Returns (dlv, dmean): dmean (= g/div, levels dtype — the d(bu)
    cotangent; d(td) is its [:L-1] slice) is non-None only on the
    single-tile path, whose kernel emits it for free."""
    L, B, n, d = levels_lm.shape
    tile_i = _pick_tile(n)
    f32 = jnp.float32
    graw = g.astype(levels_lm.dtype)

    if n <= _SMALL_BWD_N:
        # Whole row in one tile (flagship n=256 and smaller): the fused
        # single-pass kernel — scores once, complete dlv + dmean out,
        # nothing between passes because there are no passes.
        itemsize = levels_lm.dtype.itemsize
        tile_b = _fit_tile_b(
            B,
            lambda tb: (
                3 * tb * n * n * 4  # s/p + dp + ds live f32
                + 6 * tb * n * d * (itemsize + 1)  # x/g/k/dcons/outs
            ),
        )

        def spec(last):
            return pl.BlockSpec((1, tile_b, n, last), lambda g_, b: (g_, b, 0, 0))

        dlv, dmean = pl.pallas_call(
            partial(
                _consensus_bwd_small_kernel,
                side=side, radius=float(radius), attend_self=attend_self, n=n,
            ),
            out_shape=(
                jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
                jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
            ),
            grid=(L, B // tile_b),
            in_specs=[spec(d), spec(d), spec(1), spec(1)],
            out_specs=(spec(d), spec(d)),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=32 * 1024 * 1024
            ),
            interpret=interpret,
        )(levels_lm, graw, m, l)
        return dlv, dmean

    if cons is not None and _onesweep_ok(B, n, d, levels_lm.dtype.itemsize):
        dlv = _consensus_bwd_onesweep(
            levels_lm, graw, m, l, cons,
            side=side, radius=radius, attend_self=attend_self,
            interpret=interpret,
        )
        return dlv, None

    tile_j = _pick_tile(n)
    tile_b = _pick_tile_b_bwd(
        B, n, d, max(tile_i, tile_j), levels_lm.dtype.itemsize
    )
    n_ti, n_tj = n // tile_i, n // tile_j

    kw = dict(
        side=side, radius=float(radius), attend_self=attend_self,
        tile_i=tile_i, tile_j=tile_j, n=n,
    )

    def _i_spec(shape_last):
        return pl.BlockSpec(
            (1, tile_b, tile_i, shape_last), lambda g, b, i, jw: (g, b, i, 0)
        )

    def _kv_map(g, b, i, jw, _tj=n_tj):
        lo = _win_lo_tile(i, tile_i, tile_j, side, radius)
        return (g, b, jnp.minimum(lo + jw, _tj - 1), 0)

    jw_len = _win_len(tile_i, tile_j, n_tj, side, radius)
    dq, dd = pl.pallas_call(
        partial(_consensus_bwd_dq_kernel, **kw),
        out_shape=(
            jax.ShapeDtypeStruct((L, B, n, d), f32),
            jax.ShapeDtypeStruct((L, B, n, 1), f32),
        ),
        grid=(L, B // tile_b, n_ti, jw_len),
        in_specs=[
            _i_spec(d),  # x
            pl.BlockSpec((1, tile_b, tile_j, d), _kv_map),  # streamed kv
            _i_spec(d),  # dm (raw cotangent)
            _i_spec(1),  # m
            _i_spec(1),  # l
        ],
        out_specs=(_i_spec(d), _i_spec(1)),
        scratch_shapes=[
            pltpu.VMEM((tile_b, tile_i, d), f32),
            pltpu.VMEM((tile_b, tile_i, d), f32),
            pltpu.VMEM((tile_b, tile_i, 1), f32),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=32 * 1024 * 1024),
        interpret=interpret,
    )(levels_lm, levels_lm, graw, m, l)

    def _j_spec(shape_last):
        return pl.BlockSpec(
            (1, tile_b, tile_j, shape_last), lambda g, b, j, iw: (g, b, j, 0)
        )

    def _q_map(g, b, j, iw, _ti=n_ti):
        lo = _win_lo_tile(j, tile_j, tile_i, side, radius)
        return (g, b, jnp.minimum(lo + iw, _ti - 1), 0)

    def _qspec(shape_last):
        return pl.BlockSpec((1, tile_b, tile_i, shape_last), _q_map)

    iw_len = _win_len(tile_j, tile_i, n_ti, side, radius)
    dlv = pl.pallas_call(
        partial(_consensus_bwd_dkv_kernel, **kw),
        out_shape=jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
        grid=(L, B // tile_b, n_tj, iw_len),
        in_specs=[
            _j_spec(d),   # xj (resident)
            _j_spec(d),   # gj (resident, epilogue)
            _j_spec(d),   # dq j-tile (resident, epilogue)
            _qspec(d),    # streamed q i-tile
            _qspec(d),    # streamed dm i-tile
            _qspec(1),    # m
            _qspec(1),    # l
            _qspec(1),    # dd
        ],
        out_specs=_j_spec(d),
        scratch_shapes=[
            pltpu.VMEM((tile_b, tile_j, d), f32),
            pltpu.VMEM((tile_b, tile_j, d), f32),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=32 * 1024 * 1024),
        interpret=interpret,
    )(levels_lm, graw, dq, levels_lm, graw, m, l, dd)

    return dlv, None


def _xla_reference(levels_lm, bu_lm, td_lm, *, side, radius, attend_self):
    """Plain-XLA recomputation of the fused op (used for the backward pass).
    Must match the kernel's math contract bit-for-bit at the op level."""
    from glom_tpu.ops.consensus import build_local_mask, consensus_attention

    L, B, n, d = levels_lm.shape
    levels = jnp.transpose(levels_lm, (1, 2, 0, 3))  # [B, n, L, d]
    mask = build_local_mask(side, radius)
    cons = consensus_attention(levels, attend_self=attend_self, local_mask=mask)
    cons_lm = jnp.transpose(cons, (2, 0, 1, 3))  # [L, B, n, d]
    td_full = jnp.concatenate(
        [td_lm[: L - 1], jnp.zeros_like(td_lm[:1])], axis=0
    )
    div = jnp.concatenate(
        [jnp.full((L - 1, 1, 1, 1), 4.0), jnp.full((1, 1, 1, 1), 3.0)]
    ).astype(jnp.float32)
    new = (
        levels_lm.astype(jnp.float32)
        + bu_lm.astype(jnp.float32)
        + td_full.astype(jnp.float32)
        + cons_lm.astype(jnp.float32)
    ) / div
    return new.astype(levels_lm.dtype)


# Fallback dense sim-buffer cap when the runtime reports no memory stats
# (CPU interpret tests): the conservative round-3 constant.
_DENSE_SIM_LIMIT = 2 * 1024 * 1024 * 1024


def _dense_bwd_budget() -> int:
    """HBM budget for the dense backward's [L*B, n, n] f32 intermediates,
    derived from the device's reported capacity rather than a constant
    (round-3 weak item: the 2GB cap forced blockwise at shapes whose dense
    buffers demonstrably fit a 16GB chip). A 0.3 fraction leaves the rest
    for params/opt state, residual stacks, and XLA workspace — batch-aware
    because the caller multiplies by the actual [L, B, n, n] bytes."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        lim = int(stats.get("bytes_limit", 0))
        if lim > 0:
            return int(0.3 * lim)
    except Exception:  # noqa: BLE001 - platform without memory stats
        pass
    return _DENSE_SIM_LIMIT


def _use_blockwise_bwd(
    levels_shape, side, radius, bwd_impl: str, itemsize: int = 2
) -> bool:
    """Measured (n, radius) crossover between the dense-recompute VJP and
    the blockwise backward kernels (results/longctx_bench.jsonl):

      * the dense VJP — one XLA fusion over the materialized [n, n]
        similarity — wins for global consensus at every n that fits HBM
        (it runs the same matmul count at full MXU rate, no tile logic);
      * the blockwise kernels win when the local-radius band prunes most
        of the row (its grid never visits dead tiles), and are the ONLY
        option when the dense sim buffer would blow HBM (any n, since the
        streaming rewrite removed the row-residency cap).

    bwd_impl forces a side ('blockwise' / 'dense') for tests and benches.
    `itemsize` is the compute dtype's — callers on the training path pass
    the real one so the n>=4096 one-sweep branch and _fused_fwd's
    save_cons gate share one predicate (an f32 long row must not be
    routed blockwise without its cons residual).
    """
    import os
    import warnings

    if bwd_impl == "auto":
        # bench/debug override (read at trace time): lets bench_train
        # compare dispatch sides at the full train step without a config
        # field for what is a measurement knob.
        env = os.environ.get("GLOM_CONSENSUS_BWD", "auto")
        if env in ("auto", "blockwise", "dense"):
            bwd_impl = env
        else:
            warnings.warn(
                f"GLOM_CONSENSUS_BWD={env!r} ignored (valid: auto / "
                "blockwise / dense)",
                stacklevel=3,
            )
    if bwd_impl not in ("auto", "blockwise", "dense"):
        raise ValueError(
            f"bwd_impl={bwd_impl!r}: one of 'auto', 'blockwise', 'dense'"
        )
    L, B, n, d = levels_shape
    if bwd_impl == "blockwise":
        return True
    if bwd_impl == "dense":
        return False
    if radius > 0:
        reach = int(radius + 1) * side
        live = min(n, 2 * reach + _pick_tile(n))
        if 2 * live <= n:  # window covers <= half the row: sparsity pays
            return True
    # Batched-training regime AT SINGLE-TILE ROWS: the fused single-tile
    # backward keeps the scores in VMEM while the dense VJP sweeps the
    # [B, L, n, n] scores through HBM several times — measured at the
    # flagship train step (B=64, n=256): ~3950 vs 3522 col-iters/s
    # full-step. Confined to the measured region (batched AND n within
    # the single-tile kernel); the batched long-row region (B>=8,
    # n>=1024 global) is unmeasured and stays on the dense side that won
    # at B=1 (0.28 vs 0.47 ms at n=1024, 7.2 vs 7.6 ms at n=4096) until
    # its sim buffer trips the memory cap below.
    if B >= 8 and n <= _SMALL_BWD_N:
        return True
    # Long global rows: the one-sweep kernel (scores once, no inter-pass
    # HBM round trips) wins where its whole-row dq accumulator fits VMEM —
    # measured 5.61 vs 7.23 ms at n=4096 r=0 B=1 and 27.6 vs 30.5 ms at
    # n=9216 r=0 (results/longctx_bench.jsonl, round 4; the round-3
    # two-pass form LOST 38.8 vs 30.5 there). Below the crossover the
    # dense path keeps the mid-n global regime (0.281 vs 0.388 at n=1024
    # B=1). The HBM budget remains the hard gate for dense regardless.
    if n >= 4096 and _onesweep_ok(B, n, d, itemsize):
        return True
    return 2 * L * B * n * n * 4 > _dense_bwd_budget()


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret,
           bwd_impl="auto"):
    return _forward(
        levels_lm, bu_lm, td_lm,
        side=side, radius=radius, attend_self=attend_self, interpret=interpret,
    )


def _fused_fwd(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret,
               bwd_impl):
    """Training forward: ALWAYS saves the (m, l) row statistics — the flash
    logsumexp residual trade. On the blockwise side they feed the backward
    kernels; on the dense side they feed the explicit stats-based dense
    backward (one s recompute, no second forward — the jax.vjp-recompute
    form it replaces measured 17-19% over the raw dense VJP at n<=1024,
    round-3 longctx bench). The one-sweep long-row branch additionally
    saves the attention output `cons`, which makes D row-local there.
    bu/td are NOT residuals: their cotangent is g/div, values never
    needed."""
    L, B, n, d = levels_lm.shape
    blockwise = _use_blockwise_bwd(
        levels_lm.shape, side, radius, bwd_impl, levels_lm.dtype.itemsize
    )
    save_cons = (
        blockwise
        and n > _SMALL_BWD_N
        and _onesweep_ok(B, n, d, levels_lm.dtype.itemsize)
    )
    outs = _forward(
        levels_lm, bu_lm, td_lm,
        side=side, radius=radius, attend_self=attend_self,
        interpret=interpret, save_stats=True, save_cons=save_cons,
    )
    if save_cons:
        out, m, l, cons = outs
    else:
        (out, m, l), cons = outs, None
    # The backward-path decision is made HERE, once per trace, and rides
    # the residual PYTREE STRUCTURE (an empty tuple vs None has no array
    # leaves, so it stays static through the transpose): _dense_bwd_budget
    # reads allocator state, and re-evaluating it in _fused_bwd could
    # silently pick a different path than the one whose residuals were
    # saved (advisor round 4).
    return out, (levels_lm, m, l, cons, () if blockwise else None)


def _fused_bwd(side, radius, attend_self, interpret, bwd_impl, res, g):
    """The mean is linear (d bu = d td = dout/div); the attention part runs
    in the blockwise kernels (single-tile at n <= 512, one-sweep where the
    cons residual was saved, two-pass streamed otherwise — O(n) memory at
    any n) or through the explicit stats-based dense backward where that
    measured faster — decided ONCE in _fused_fwd and carried in the
    residual structure."""
    from glom_tpu.models.core import contribution_divisor  # lazy: no cycle

    levels_lm, m, l, cons, blockwise_flag = res
    L, B, n, d = levels_lm.shape
    f32 = jnp.float32
    if blockwise_flag is not None:
        # The kernels take the RAW cotangent, apply the divisor in-kernel
        # (from the level grid index), and emit the COMPLETE dlv in the
        # levels dtype — no divided/partial-sum copies of g hit HBM. The
        # single-tile kernel also emits dmean (the d(bu)/d(td) cotangent)
        # so the caller-side divide+downcast sweep of g disappears too.
        dlv, dmean_k = _consensus_update_bwd(
            levels_lm, g, m, l, cons,
            side=side, radius=radius, attend_self=attend_self,
            interpret=interpret,
        )
        if dmean_k is not None:
            return dlv, dmean_k, dmean_k[: L - 1]
    else:
        # Explicit dense backward from the saved stats: the same math as
        # the single-tile kernel (_small_bwd_math), batched over [L*B] in
        # XLA — recomputes s once, never re-runs the forward's softmax
        # reductions or attn@v.
        div = contribution_divisor(L, dtype=f32).reshape(L, 1, 1, 1)
        dcons = (g.astype(f32) / div).reshape(L * B, n, d)
        dlv = _small_bwd_math(
            levels_lm.reshape(L * B, n, d), dcons,
            m.reshape(L * B, n, 1), l.reshape(L * B, n, 1),
            side=side, radius=radius, attend_self=attend_self, n=n,
        ).reshape(L, B, n, d).astype(levels_lm.dtype)
    div = contribution_divisor(L, dtype=f32).reshape(L, 1, 1, 1)
    dmean = g.astype(f32) / div
    return (
        dlv,
        dmean.astype(levels_lm.dtype),
        dmean[: L - 1].astype(levels_lm.dtype),
    )


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_consensus_update(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float = 0.0,
    attend_self: bool = False,
    interpret: bool = False,
    bwd_impl: str = "auto",
) -> jnp.ndarray:
    """new_levels = (levels + bu + pad(td) + consensus(levels)) / div, fused.

    levels_lm: [L, B, n, d] level-major; bu_lm: [L, B, n, d];
    td_lm: [L-1, B, n, d] (top level's zero contribution is implicit).
    Returns [L, B, n, d]. Falls back to the XLA composition off-TPU.
    bwd_impl: 'auto' dispatches the backward between the dense-recompute
    VJP and the streamed blockwise kernels by the measured (n, radius)
    crossover; 'blockwise'/'dense' force a side (tests, benches).
    """
    import os

    L, B, n, d = levels_lm.shape
    on_tpu = jax.devices()[0].platform == "tpu"
    supported = d % 128 == 0 and n % 8 == 0 and L >= 2
    if not supported or not (on_tpu or interpret):
        return _xla_reference(
            levels_lm, bu_lm, td_lm,
            side=side, radius=radius, attend_self=attend_self,
        )
    # Auto-resolved-dense small/mid rows: the XLA dense op wins BOTH
    # directions there (fwd 0.118 vs 0.139 ms, autodiff bwd 0.281 vs 0.354
    # at n=1024 B=1 — longctx bench), so hand the WHOLE op to XLA autodiff:
    # zero custom_vjp overhead by construction (round-3 weak #3's 17%).
    # Forced sides (bwd_impl or the env override) keep the custom_vjp so
    # tests and A/B benches still reach the kernel paths; n >= 4096 keeps
    # the hybrid (the Pallas forward wins there: 1.66 vs 3.13 ms).
    forced = (
        bwd_impl != "auto"
        or os.environ.get("GLOM_CONSENSUS_BWD", "auto") != "auto"
    )
    if (
        not forced
        and n < 4096
        and not _use_blockwise_bwd(
            (L, B, n, d), side, radius, bwd_impl, levels_lm.dtype.itemsize
        )
    ):
        return _xla_reference(
            levels_lm, bu_lm, td_lm,
            side=side, radius=radius, attend_self=attend_self,
        )
    return _fused(
        levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret, bwd_impl
    )
