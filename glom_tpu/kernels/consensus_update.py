"""Pallas TPU kernel: blockwise consensus attention fused with the 4-way
mean column update.

Reference parity: ConsensusAttention.forward + the update mean
(glom_pytorch/glom_pytorch.py:54-71 and :124-140). One kernel program
computes, for one (level g, image b, row-tile i):

    cons = softmax_j( q_i . normalize(k)_j * d^-1/2  [dual masks] ) @ v
    out  = (levels_i + bottom_up_i + top_down_i + cons) / div_g

with a flash-style ONLINE softmax over j-tiles — the [n, n] similarity is
never materialized (O(n) memory in the patch axis), which is the
long-context path SURVEY.md §2.2 calls for. Both reference mask semantics
live in the inner loop:

  * attend_self=False: the DIAGONAL similarity is REPLACED by the soft
    -5e-4 penalty (reference TOKEN_ATTEND_SELF_VALUE, :9/:61-63);
  * local radius > 0: pairs farther than `radius` in Euclidean patch-grid
    distance are hard-masked to -3e38 (reference cdist buffer, :42-52).
    The mask is computed in-register from iota (no [n, n] HBM buffer at
    all — the reference's O(n^2) init-time cost disappears), and j-tiles
    that are ENTIRELY outside the radius band are skipped (block
    sparsity): rows i and j can only interact if their grid rows differ
    by <= radius, so the live j-window per i-tile is static arithmetic.

The epilogue folds in the per-level mean (4 contributions, 3 at the top
level — reference :121-122) and the zero top-down of the top level
(reference :130 F.pad) by masking the g = L-1 top-down tile, so XLA's
separate pad + add + divide HBM sweeps disappear.

Layout: level-major [L, B, n, d] ("lm") — the batched-matmul-natural
layout; glom_tpu.models.core keeps the scan carry in this layout so no
transposes appear between kernels.

Backward: custom_vjp that recomputes the forward in plain XLA (dense
consensus from ops/consensus.py) and differentiates that — exactly
correct (same math contract, locked by tests), matmul-heavy, and saves
nothing but levels/bu/td, the flash-attention residual trade.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE

_NEG_MAX = float(jnp.finfo(jnp.float32).min)


def _row_col(idx, side):
    """Patch-grid (row, col) coordinates of flat patch indices."""
    return idx // side, idx % side


def _consensus_update_kernel(
    x_ref,      # [1, TB, TI, d] levels q/self tile
    kv_ref,     # [1, TB, n, d]  full rows of levels for (g, b-tile): k and v
    bu_ref,     # [1, TB, TI, d] bottom-up contribution tile
    td_ref,     # [1, TB, TI, d] top-down tile (index-clamped at the top level)
    out_ref,    # [1, TB, TI, d]
    *,
    levels_count: int,
    side: int,
    radius: float,
    attend_self: bool,
    tile_i: int,
    tile_j: int,
    n: int,
):
    """One program: a (level g, image-tile, row-tile i) block. The TB images
    ride the batch dimension of a single batched dot_general per j-step, so
    small-n configs still feed the MXU one large op instead of TB tiny ones.
    """
    g = pl.program_id(0)
    i = pl.program_id(2)
    tb = x_ref.shape[1]
    d = x_ref.shape[-1]
    scale = d ** -0.5

    x = x_ref[0]  # [TB, TI, d]
    q32 = x.astype(jnp.float32)

    row_ids = i * tile_i + jax.lax.broadcasted_iota(jnp.int32, (tile_i, tile_j), 0)
    ri, ci = _row_col(row_ids, side)

    n_j = n // tile_j

    # Block sparsity for the local mask: patches interact only when their
    # grid rows differ by <= radius, i.e. flat indices differ by less than
    # (radius + 1) * side. The live j-window for this i-tile (i is traced,
    # so the window is int32 arithmetic; fori_loop takes dynamic bounds):
    if radius > 0:
        reach = int(radius + 1) * side
        lo = i * tile_i - reach
        hi = i * tile_i + tile_i + reach
        j_lo = jnp.maximum(lo // tile_j, 0)
        j_hi = jnp.minimum(-(-hi // tile_j), n_j)
    else:
        j_lo, j_hi = 0, n_j

    m0 = jnp.full((tb, tile_i, 1), _NEG_MAX, jnp.float32)
    l0 = jnp.zeros((tb, tile_i, 1), jnp.float32)
    acc0 = jnp.zeros((tb, tile_i, d), jnp.float32)

    def j_body(j, carry):
        m, l, acc = carry
        kv = kv_ref[0, :, pl.ds(j * tile_j, tile_j), :]  # [TB, TJ, d]
        kv32 = kv.astype(jnp.float32)
        # k-only L2 normalization (reference :56): v stays raw. Matches
        # helpers.l2norm: x / max(||x||, 1e-12).
        norm = jnp.sqrt(jnp.sum(kv32 * kv32, axis=-1, keepdims=True))
        k = (kv32 / jnp.maximum(norm, 1e-12)).astype(x.dtype)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [TB, TI, TJ]

        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        if not attend_self:
            s = jnp.where((row_ids == col_ids)[None], TOKEN_ATTEND_SELF_VALUE, s)
        if radius > 0:
            rj, cj = _row_col(col_ids, side)
            dist2 = (ri - rj) ** 2 + (ci - cj) ** 2
            s = jnp.where(
                (dist2.astype(jnp.float32) > radius * radius)[None], _NEG_MAX, s
            )

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # Downcast the probabilities for the MXU, matching the dense op's
        # softmax(...).astype(levels.dtype) before attn @ v.
        pv = jax.lax.dot_general(
            p.astype(x.dtype), kv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(j_lo, j_hi, j_body, (m0, l0, acc0))
    cons = acc / l

    bu = bu_ref[0].astype(jnp.float32)
    td = td_ref[0].astype(jnp.float32)
    # Top level: no top-down contribution (its tile is index-clamped junk)
    # and a 3-way divisor (reference :121-122, :130).
    is_top = g == levels_count - 1
    td = jnp.where(is_top, 0.0, td)
    div = jnp.where(is_top, 3.0, 4.0)
    new = (q32 + bu + td + cons) / div
    out_ref[0] = new.astype(out_ref.dtype)


def _pick_tile(n: int, cap: int = 256) -> int:
    for t in (512, 256, 128, 64, 32, 16, 8):
        if t <= cap and n % t == 0 and t <= n:
            return t
    return n


def _pick_tile_b(B: int, n: int, d: int, tile_i: int, tile_j: int, itemsize: int) -> int:
    """Largest batch tile dividing B that keeps the working set well under
    VMEM: ~2x-buffered in/out blocks + f32 accumulators + the sim tile."""
    budget = 12 * 1024 * 1024
    for tb in (8, 4, 2, 1):
        if B % tb != 0:
            continue
        blocks = 5 * tb * tile_i * d * itemsize * 2  # x/bu/td/out/kv, 2x buffered
        kv_extra = tb * (n - tile_i) * d * itemsize * 2 if n > tile_i else 0
        scratch = tb * tile_i * (d + 1) * 4 * 2 + tb * tile_i * tile_j * 4
        if blocks + kv_extra + scratch <= budget:
            return tb
    return 1


def _forward(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool,
) -> jnp.ndarray:
    L, B, n, d = levels_lm.shape
    tile_i = _pick_tile(n)
    # Global consensus: a wider j-tile halves the online-softmax correction
    # steps (measured 1.91 -> 1.69 ms at n=4096, beating the dense XLA
    # path). Local radius: keep j-tiles at 256 so the block-sparse window
    # stays fine-grained (a 512 tile erases the skip at side<=32).
    tile_j = _pick_tile(n, cap=512 if radius <= 0 else 256)
    tile_b = _pick_tile_b(B, n, d, tile_i, tile_j, levels_lm.dtype.itemsize)
    grid = (L, B // tile_b, n // tile_i)

    kernel = partial(
        _consensus_update_kernel,
        levels_count=L,
        side=side,
        radius=float(radius),
        attend_self=attend_self,
        tile_i=tile_i,
        tile_j=tile_j,
        n=n,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # x
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, i: (g, b, 0, 0)),  # kv
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # bu
            # td has L-1 groups; clamp the top level's index (masked in-kernel)
            pl.BlockSpec(
                (1, tile_b, tile_i, d),
                lambda g, b, i, _L=L: (jnp.minimum(g, _L - 2), b, i, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),
        interpret=interpret,
    )(levels_lm, levels_lm, bu_lm, td_lm)


def _xla_reference(levels_lm, bu_lm, td_lm, *, side, radius, attend_self):
    """Plain-XLA recomputation of the fused op (used for the backward pass).
    Must match the kernel's math contract bit-for-bit at the op level."""
    from glom_tpu.ops.consensus import build_local_mask, consensus_attention

    L, B, n, d = levels_lm.shape
    levels = jnp.transpose(levels_lm, (1, 2, 0, 3))  # [B, n, L, d]
    mask = build_local_mask(side, radius)
    cons = consensus_attention(levels, attend_self=attend_self, local_mask=mask)
    cons_lm = jnp.transpose(cons, (2, 0, 1, 3))  # [L, B, n, d]
    td_full = jnp.concatenate(
        [td_lm[: L - 1], jnp.zeros_like(td_lm[:1])], axis=0
    )
    div = jnp.concatenate(
        [jnp.full((L - 1, 1, 1, 1), 4.0), jnp.full((1, 1, 1, 1), 3.0)]
    ).astype(jnp.float32)
    new = (
        levels_lm.astype(jnp.float32)
        + bu_lm.astype(jnp.float32)
        + td_full.astype(jnp.float32)
        + cons_lm.astype(jnp.float32)
    ) / div
    return new.astype(levels_lm.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret):
    return _forward(
        levels_lm, bu_lm, td_lm,
        side=side, radius=radius, attend_self=attend_self, interpret=interpret,
    )


def _fused_fwd(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret):
    out = _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret)
    return out, (levels_lm, bu_lm, td_lm)


def _fused_bwd(side, radius, attend_self, interpret, res, g):
    levels_lm, bu_lm, td_lm = res
    _, vjp = jax.vjp(
        lambda lv, bu, td: _xla_reference(
            lv, bu, td, side=side, radius=radius, attend_self=attend_self
        ),
        levels_lm, bu_lm, td_lm,
    )
    return vjp(g)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_consensus_update(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float = 0.0,
    attend_self: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """new_levels = (levels + bu + pad(td) + consensus(levels)) / div, fused.

    levels_lm: [L, B, n, d] level-major; bu_lm: [L, B, n, d];
    td_lm: [L-1, B, n, d] (top level's zero contribution is implicit).
    Returns [L, B, n, d]. Falls back to the XLA composition off-TPU.
    """
    L, B, n, d = levels_lm.shape
    on_tpu = jax.devices()[0].platform == "tpu"
    supported = d % 128 == 0 and n % 8 == 0 and L >= 2
    if not supported or not (on_tpu or interpret):
        return _xla_reference(
            levels_lm, bu_lm, td_lm,
            side=side, radius=radius, attend_self=attend_self,
        )
    return _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret)
