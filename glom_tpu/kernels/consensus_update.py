"""Pallas TPU kernel: blockwise consensus attention fused with the 4-way
mean column update.

Reference parity: ConsensusAttention.forward + the update mean
(glom_pytorch/glom_pytorch.py:54-71 and :124-140). One kernel program
computes, for one (level g, image b, row-tile i):

    cons = softmax_j( q_i . normalize(k)_j * d^-1/2  [dual masks] ) @ v
    out  = (levels_i + bottom_up_i + top_down_i + cons) / div_g

with a flash-style ONLINE softmax over j-tiles — the [n, n] similarity is
never materialized (O(n) memory in the patch axis), which is the
long-context path SURVEY.md §2.2 calls for. Both reference mask semantics
live in the inner loop:

  * attend_self=False: the DIAGONAL similarity is REPLACED by the soft
    -5e-4 penalty (reference TOKEN_ATTEND_SELF_VALUE, :9/:61-63);
  * local radius > 0: pairs farther than `radius` in Euclidean patch-grid
    distance are hard-masked to -3e38 (reference cdist buffer, :42-52).
    The mask is computed in-register from iota (no [n, n] HBM buffer at
    all — the reference's O(n^2) init-time cost disappears), and j-tiles
    that are ENTIRELY outside the radius band are skipped (block
    sparsity): rows i and j can only interact if their grid rows differ
    by <= radius, so the live j-window per i-tile is static arithmetic.

The epilogue folds in the per-level mean (4 contributions, 3 at the top
level — reference :121-122) and the zero top-down of the top level
(reference :130 F.pad) by masking the g = L-1 top-down tile, so XLA's
separate pad + add + divide HBM sweeps disappear.

Layout: level-major [L, B, n, d] ("lm") — the batched-matmul-natural
layout; glom_tpu.models.core keeps the scan carry in this layout so no
transposes appear between kernels.

Backward: custom_vjp over two more Pallas kernels (flash-attention-style,
saving nothing but levels/bu/td): a dq pass that recomputes the row
statistics and consensus online (for D = rowsum(dcons*cons)) and
accumulates dq over the j-window, and a dkv pass gridded over j that
accumulates dv and dk over the i-window and pushes dk through the
row-local k-normalization VJP. The [n, n] matrix is never materialized in
either direction, so long-context TRAINING is O(n) memory too; both
passes skip dead tiles under the local-radius band. The linear mean part
(d bu, d td, the direct levels term) is plain XLA glue in _fused_bwd.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glom_tpu.utils.helpers import TOKEN_ATTEND_SELF_VALUE

_NEG_MAX = float(jnp.finfo(jnp.float32).min)

# Max bytes of ONE full [n, d] levels row for the blockwise BACKWARD kernels
# (the dkv pass holds two such rows resident in VMEM); beyond this the
# custom VJP falls back to the dense recompute.
_BWD_ROW_LIMIT = 4 * 1024 * 1024


def _row_col(idx, side):
    """Patch-grid (row, col) coordinates of flat patch indices."""
    return idx // side, idx % side


def _consensus_update_kernel(
    x_ref,      # [1, TB, TI, d] levels q/self tile
    kv_ref,     # [1, TB, n, d]  full rows of levels for (g, b-tile): k and v
    bu_ref,     # [1, TB, TI, d] bottom-up contribution tile
    td_ref,     # [1, TB, TI, d] top-down tile (index-clamped at the top level)
    out_ref,    # [1, TB, TI, d]
    *,
    levels_count: int,
    side: int,
    radius: float,
    attend_self: bool,
    tile_i: int,
    tile_j: int,
    n: int,
):
    """One program: a (level g, image-tile, row-tile i) block. The TB images
    ride the batch dimension of a single batched dot_general per j-step, so
    small-n configs still feed the MXU one large op instead of TB tiny ones.
    """
    g = pl.program_id(0)
    i = pl.program_id(2)
    tb = x_ref.shape[1]
    d = x_ref.shape[-1]
    scale = d ** -0.5

    x = x_ref[0]  # [TB, TI, d]
    q32 = x.astype(jnp.float32)

    row_ids = i * tile_i + jax.lax.broadcasted_iota(jnp.int32, (tile_i, tile_j), 0)
    ri, ci = _row_col(row_ids, side)

    # Block sparsity for the local mask: the live j-window for this i-tile
    # (i is traced, so the window is int32 arithmetic; fori_loop takes
    # dynamic bounds). Shared with both backward kernels via _window.
    j_lo, j_hi = _window(i * tile_i, tile_i, tile_j, n // tile_j, side, radius)

    m0 = jnp.full((tb, tile_i, 1), _NEG_MAX, jnp.float32)
    l0 = jnp.zeros((tb, tile_i, 1), jnp.float32)
    acc0 = jnp.zeros((tb, tile_i, d), jnp.float32)

    def j_body(j, carry):
        m, l, acc = carry
        kv = kv_ref[0, :, pl.ds(j * tile_j, tile_j), :]  # [TB, TJ, d]
        # k-only L2 normalization (reference :56): v stays raw.
        k = _normalized_k(kv)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [TB, TI, TJ]

        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        if not attend_self:
            s = jnp.where((row_ids == col_ids)[None], TOKEN_ATTEND_SELF_VALUE, s)
        if radius > 0:
            rj, cj = _row_col(col_ids, side)
            dist2 = (ri - rj) ** 2 + (ci - cj) ** 2
            s = jnp.where(
                (dist2.astype(jnp.float32) > radius * radius)[None], _NEG_MAX, s
            )

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # Downcast the probabilities for the MXU, matching the dense op's
        # softmax(...).astype(levels.dtype) before attn @ v.
        pv = jax.lax.dot_general(
            p.astype(x.dtype), kv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(j_lo, j_hi, j_body, (m0, l0, acc0))
    cons = acc / l

    bu = bu_ref[0].astype(jnp.float32)
    td = td_ref[0].astype(jnp.float32)
    # Top level: no top-down contribution (its tile is index-clamped junk)
    # and a 3-way divisor (reference :121-122, :130).
    is_top = g == levels_count - 1
    td = jnp.where(is_top, 0.0, td)
    div = jnp.where(is_top, 3.0, 4.0)
    new = (q32 + bu + td + cons) / div
    out_ref[0] = new.astype(out_ref.dtype)


def _pick_tile(n: int, cap: int = 256) -> int:
    for t in (512, 256, 128, 64, 32, 16, 8):
        if t <= cap and n % t == 0 and t <= n:
            return t
    return n


def _pick_tile_b(B: int, n: int, d: int, tile_i: int, tile_j: int, itemsize: int) -> int:
    """Largest batch tile dividing B that keeps the working set well under
    VMEM: ~2x-buffered in/out blocks + f32 accumulators + the sim tile."""
    budget = 12 * 1024 * 1024
    for tb in (8, 4, 2, 1):
        if B % tb != 0:
            continue
        blocks = 5 * tb * tile_i * d * itemsize * 2  # x/bu/td/out/kv, 2x buffered
        kv_extra = tb * (n - tile_i) * d * itemsize * 2 if n > tile_i else 0
        scratch = tb * tile_i * (d + 1) * 4 * 2 + tb * tile_i * tile_j * 4
        if blocks + kv_extra + scratch <= budget:
            return tb
    return 1


def _forward(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float,
    attend_self: bool,
    interpret: bool,
) -> jnp.ndarray:
    L, B, n, d = levels_lm.shape
    tile_i = _pick_tile(n)
    # Global consensus: a wider j-tile halves the online-softmax correction
    # steps (measured 1.91 -> 1.69 ms at n=4096, beating the dense XLA
    # path). Local radius: keep j-tiles at 256 so the block-sparse window
    # stays fine-grained (a 512 tile erases the skip at side<=32).
    tile_j = _pick_tile(n, cap=512 if radius <= 0 else 256)
    tile_b = _pick_tile_b(B, n, d, tile_i, tile_j, levels_lm.dtype.itemsize)
    grid = (L, B // tile_b, n // tile_i)

    kernel = partial(
        _consensus_update_kernel,
        levels_count=L,
        side=side,
        radius=float(radius),
        attend_self=attend_self,
        tile_i=tile_i,
        tile_j=tile_j,
        n=n,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # x
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, i: (g, b, 0, 0)),  # kv
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),  # bu
            # td has L-1 groups; clamp the top level's index (masked in-kernel)
            pl.BlockSpec(
                (1, tile_b, tile_i, d),
                lambda g, b, i, _L=L: (jnp.minimum(g, _L - 2), b, i, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),
        interpret=interpret,
    )(levels_lm, levels_lm, bu_lm, td_lm)


def _normalized_k(kv_tile):
    """k-only L2 normalization in f32, downcast to the compute dtype
    (reference :56 / helpers.l2norm: x / max(||x||, 1e-12))."""
    kv32 = kv_tile.astype(jnp.float32)
    norm = jnp.sqrt(jnp.sum(kv32 * kv32, axis=-1, keepdims=True))
    return (kv32 / jnp.maximum(norm, 1e-12)).astype(kv_tile.dtype)


def _window(center_lo, extent, tile, n_tiles, side, radius):
    """Live tile-window [lo, hi) along the opposite attention axis: flat
    indices interact only when their grid rows differ by <= radius, i.e.
    they are within (radius + 1) * side flat positions."""
    if radius <= 0:
        return 0, n_tiles
    reach = int(radius + 1) * side
    lo = center_lo - reach
    hi = center_lo + extent + reach
    return jnp.maximum(lo // tile, 0), jnp.minimum(-(-hi // tile), n_tiles)


def _consensus_bwd_dq_kernel(
    x_ref,      # [1, TB, TI, d]  levels q tile
    kv_ref,     # [1, TB, n, d]   full levels rows (k and v)
    dm_ref,     # [1, TB, TI, d]  RAW output-cotangent tile (compute dtype;
                #                 the 4-vs-3 mean divisor is applied HERE,
                #                 from the level grid index — feeding the
                #                 kernel g directly avoids a separate
                #                 divide+downcast HBM sweep in the caller)
    dq_ref,     # [1, TB, TI, d]  f32
    m_ref,      # [1, TB, TI, 1]  f32 row max (saved for the dkv kernel)
    l_ref,      # [1, TB, TI, 1]  f32 row softmax denominator
    dd_ref,     # [1, TB, TI, 1]  f32 D_i = sum_d dcons_i * cons_i
    *, side, radius, attend_self, tile_i, tile_j, n,
):
    """Pass 1 of the blockwise consensus backward (flash-attention style,
    adapted to GLOM: q = v = levels raw, k = normalize(levels), soft -5e-4
    REPLACED diagonal, hard local mask). Nothing was saved by the forward
    (the flash residual trade), so the first j-loop recomputes the row
    statistics (m, l) and the consensus output (for D = rowsum(dcons*cons));
    the second j-loop forms ds = p*(dP - D) and accumulates
    dq_i = scale * sum_j ds_ij k_j. The [n, n] attention matrix is never
    materialized — O(n) memory, same block-sparse j-window skipping as the
    forward."""
    i = pl.program_id(2)
    tb = x_ref.shape[1]
    d = x_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32

    x = x_ref[0]
    # dcons = g / div: top level (last grid-0 index) averages 3 contributions
    div = jnp.where(pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0)
    dcons = dm_ref[0].astype(f32) / div
    row_ids = i * tile_i + jax.lax.broadcasted_iota(jnp.int32, (tile_i, tile_j), 0)
    ri, ci = _row_col(row_ids, side)
    j_lo, j_hi = _window(i * tile_i, tile_i, tile_j, n // tile_j, side, radius)

    def scores(j):
        kv = kv_ref[0, :, pl.ds(j * tile_j, tile_j), :]
        k = _normalized_k(kv)
        s = (
            jax.lax.dot_general(
                x, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )
        col_ids = j * tile_j + jax.lax.broadcasted_iota(
            jnp.int32, (tile_i, tile_j), 1
        )
        if not attend_self:
            s = jnp.where((row_ids == col_ids)[None], TOKEN_ATTEND_SELF_VALUE, s)
        if radius > 0:
            rj, cj = _row_col(col_ids, side)
            dist2 = (ri - rj) ** 2 + (ci - cj) ** 2
            s = jnp.where(
                (dist2.astype(f32) > radius * radius)[None], _NEG_MAX, s
            )
        return s, k, kv, col_ids

    def stat_body(j, carry):
        m, l, acc = carry
        s, _, kv, _ = scores(j)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(x.dtype), kv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        return m_new, l_new, acc * corr + pv

    m0 = jnp.full((tb, tile_i, 1), _NEG_MAX, f32)
    l0 = jnp.zeros((tb, tile_i, 1), f32)
    acc0 = jnp.zeros((tb, tile_i, d), f32)
    m, l, acc = jax.lax.fori_loop(j_lo, j_hi, stat_body, (m0, l0, acc0))
    cons = acc / l
    dd = jnp.sum(dcons * cons, axis=-1, keepdims=True)  # [TB, TI, 1]

    def dq_body(j, dq):
        s, k, kv, col_ids = scores(j)
        p = jnp.exp(s - m) / l  # normalized probabilities, f32
        dp = jax.lax.dot_general(
            dcons.astype(x.dtype), kv, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=f32,
        )  # dP_ij = dcons_i . v_j
        ds = p * (dp - dd)
        if not attend_self:
            # the diagonal was REPLACED by a constant: no grad flows there
            ds = jnp.where((row_ids == col_ids)[None], 0.0, ds)
        dq_step = jax.lax.dot_general(
            ds.astype(x.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        return dq + dq_step

    dq = jax.lax.fori_loop(
        j_lo, j_hi, dq_body, jnp.zeros((tb, tile_i, d), f32)
    )
    dq_ref[0] = dq * scale
    m_ref[0] = m
    l_ref[0] = l
    dd_ref[0] = dd


def _consensus_bwd_dkv_kernel(
    xj_ref,     # [1, TB, TJ, d]  levels j-tile (k_j, v_j live here)
    q_ref,      # [1, TB, n, d]   full levels rows (queries)
    dm_ref,     # [1, TB, n, d]   full RAW output-cotangent rows (compute
                #                 dtype; the mean divisor is applied here,
                #                 same trade as in the dq kernel)
    dq_ref,     # [1, TB, TJ, d]  f32 dq tile from pass 1 (j-aligned)
    m_ref,      # [1, TB, n, 1]   f32 stats from the dq kernel
    l_ref,      # [1, TB, n, 1]
    dd_ref,     # [1, TB, n, 1]
    out_ref,    # [1, TB, TJ, d]  levels dtype: the COMPLETE dlevels tile
                #                 (dmean + dq + dv + normalizeVJP(dk)) —
                #                 folding the sum here removes the separate
                #                 XLA add/convert HBM sweeps
    *, side, radius, attend_self, tile_i, tile_j, n,
):
    """Pass 2: for each j-tile, loop the i-window and accumulate
    dv_j = sum_i p_ij dcons_i and dk_j = scale * sum_i ds_ij q_i, push dk
    through the k-normalization VJP (row-local), then finish dlevels in the
    epilogue: out_j = g_j/div + dq_j + dv_j + dxn_j, downcast once."""
    j = pl.program_id(2)
    tb = xj_ref.shape[1]
    d = xj_ref.shape[-1]
    scale = d ** -0.5
    f32 = jnp.float32

    xj = xj_ref[0]            # [TB, TJ, d] raw levels (v_j; k_j after norm)
    k = _normalized_k(xj)
    # g / div applied via the LINEAR uses of dcons: dv and dP are both
    # linear in dcons, so the divide moves onto the accumulated dots.
    inv_div = 1.0 / jnp.where(pl.program_id(0) == pl.num_programs(0) - 1, 3.0, 4.0)
    col_ids = j * tile_j + jax.lax.broadcasted_iota(jnp.int32, (tile_j, tile_i), 0)
    rj, cj = _row_col(col_ids, side)
    i_lo, i_hi = _window(j * tile_j, tile_j, tile_i, n // tile_i, side, radius)

    def i_body(i, carry):
        dv, dk = carry
        q = q_ref[0, :, pl.ds(i * tile_i, tile_i), :]        # [TB, TI, d]
        dcons = dm_ref[0, :, pl.ds(i * tile_i, tile_i), :]   # [TB, TI, d]
        m = m_ref[0, :, pl.ds(i * tile_i, tile_i), 0]        # [TB, TI]
        l = l_ref[0, :, pl.ds(i * tile_i, tile_i), 0]
        dd = dd_ref[0, :, pl.ds(i * tile_i, tile_i), 0]

        # s2[b, tj, ti] = s[i, j] transposed
        s2 = (
            jax.lax.dot_general(
                k, q, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * scale
        )  # [TB, TJ, TI]
        row_ids = i * tile_i + jax.lax.broadcasted_iota(
            jnp.int32, (tile_j, tile_i), 1
        )  # query index along the LAST axis here
        if not attend_self:
            s2 = jnp.where((col_ids == row_ids)[None], TOKEN_ATTEND_SELF_VALUE, s2)
        if radius > 0:
            ri2, ci2 = _row_col(row_ids, side)
            dist2 = (rj - ri2) ** 2 + (cj - ci2) ** 2
            s2 = jnp.where(
                (dist2.astype(f32) > radius * radius)[None], _NEG_MAX, s2
            )

        p2 = jnp.exp(s2 - m[:, None, :]) / l[:, None, :]     # [TB, TJ, TI]
        p2c = p2.astype(xj.dtype)
        dv_step = jax.lax.dot_general(
            p2c, dcons, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        dp2 = (
            jax.lax.dot_general(
                xj, dcons, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=f32,
            )
            * inv_div
        )  # dP2[b, tj, ti] = v_j . (dcons_i / div_i); dd is already divided
        ds2 = p2 * (dp2 - dd[:, None, :])
        if not attend_self:
            ds2 = jnp.where((col_ids == row_ids)[None], 0.0, ds2)
        dk_step = jax.lax.dot_general(
            ds2.astype(xj.dtype), q, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=f32,
        )
        return dv + dv_step, dk + dk_step

    dv0 = jnp.zeros((tb, tile_j, d), f32)
    dk0 = jnp.zeros((tb, tile_j, d), f32)
    dv, dk = jax.lax.fori_loop(i_lo, i_hi, i_body, (dv0, dk0))
    dv = dv * inv_div  # dv accumulated against the RAW cotangent rows
    dk = dk * scale

    # k-normalization VJP (row-local): k = x / max(||x||, eps).
    x32 = xj.astype(f32)
    r = jnp.sqrt(jnp.sum(x32 * x32, axis=-1, keepdims=True))
    inv = 1.0 / jnp.maximum(r, 1e-12)
    a = jnp.sum(dk * x32, axis=-1, keepdims=True)
    dxn = dk * inv - jnp.where(r >= 1e-12, a * x32 * inv * inv / r, 0.0)
    # Epilogue: complete dlevels for this j-tile. dmean_j = g_j / div.
    gj = dm_ref[0, :, pl.ds(j * tile_j, tile_j), :].astype(f32) * inv_div
    out_ref[0] = (gj + dq_ref[0] + dv + dxn).astype(out_ref.dtype)


def _pick_tile_b_bwd(B: int, n: int, d: int, tile: int, itemsize: int) -> int:
    """Batch tile for the BACKWARD kernels, whose working set is heavier
    than the forward's: the dkv pass keeps TWO full-row operands resident
    (q and the raw cotangent, levels dtype) plus an f32 dq input tile and
    a levels-dtype out tile, and the dq pass one full-row operand plus the
    f32 dq block — the forward's budget model undercounts that by ~2x in
    the long-context regime."""
    budget = 12 * 1024 * 1024
    for tb in (8, 4, 2, 1):
        if B % tb != 0:
            continue
        full_rows = 2 * tb * n * d * itemsize          # q + dcons, resident
        # in tiles (xj dtype + dq f32) + out tile (dtype), 2x buffered
        tiles = tb * tile * d * (2 * itemsize + 4) * 2
        stats = 3 * tb * n * 4
        scratch = 2 * tb * tile * tile * 4 + 2 * tb * tile * d * 4  # s2/ds + dv/dk acc
        if full_rows + tiles + stats + scratch <= budget:
            return tb
    return 1


def _consensus_update_bwd(levels_lm, g, *, side, radius, attend_self, interpret):
    """Blockwise backward for the fused consensus+update: returns the
    COMPLETE d(levels) = dmean + dq + (dv + dk-through-normalization), in
    the levels dtype. `g` is the RAW output cotangent in the compute dtype
    — the 4-vs-3 mean divisor is applied inside the kernels from the level
    grid index, and the dkv pass's epilogue folds dmean + dq into its
    output, so neither a divided copy of g nor the f32 partial sums ever
    make a separate HBM round trip."""
    L, B, n, d = levels_lm.shape
    # Rows here are guaranteed <= _BWD_ROW_LIMIT bytes (bigger shapes take
    # _fused_bwd's dense fallback), so the default 256 tiles always fit.
    tile_i = _pick_tile(n)
    tile_j = _pick_tile(n)
    tile_b = _pick_tile_b_bwd(
        B, n, d, max(tile_i, tile_j), levels_lm.dtype.itemsize
    )
    grid = (L, B // tile_b, n // tile_i)
    f32 = jnp.float32

    kw = dict(
        side=side, radius=float(radius), attend_self=attend_self,
        tile_i=tile_i, tile_j=tile_j, n=n,
    )
    dq, m_, l_, dd_ = pl.pallas_call(
        partial(_consensus_bwd_dq_kernel, **kw),
        out_shape=(
            jax.ShapeDtypeStruct((L, B, n, d), f32),
            jax.ShapeDtypeStruct((L, B, n, 1), f32),
            jax.ShapeDtypeStruct((L, B, n, 1), f32),
            jax.ShapeDtypeStruct((L, B, n, 1), f32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, i: (g, b, 0, 0)),
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, tile_b, tile_i, d), lambda g, b, i: (g, b, i, 0)),
            pl.BlockSpec((1, tile_b, tile_i, 1), lambda g, b, i: (g, b, i, 0)),
            pl.BlockSpec((1, tile_b, tile_i, 1), lambda g, b, i: (g, b, i, 0)),
            pl.BlockSpec((1, tile_b, tile_i, 1), lambda g, b, i: (g, b, i, 0)),
        ),
        # At the long-context limit (n=4096 rows, _BWD_ROW_LIMIT) the
        # resident rows + tiles land just over Mosaic's default 16MB
        # scoped-vmem budget; raise the scope (v5e has 128MB physical).
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=48 * 1024 * 1024),
        interpret=interpret,
    )(levels_lm, levels_lm, g.astype(levels_lm.dtype))

    grid_j = (L, B // tile_b, n // tile_j)
    dlv = pl.pallas_call(
        partial(_consensus_bwd_dkv_kernel, **kw),
        out_shape=jax.ShapeDtypeStruct((L, B, n, d), levels_lm.dtype),
        grid=grid_j,
        in_specs=[
            pl.BlockSpec((1, tile_b, tile_j, d), lambda g, b, j: (g, b, j, 0)),
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, j: (g, b, 0, 0)),
            pl.BlockSpec((1, tile_b, n, d), lambda g, b, j: (g, b, 0, 0)),
            pl.BlockSpec((1, tile_b, tile_j, d), lambda g, b, j: (g, b, j, 0)),
            pl.BlockSpec((1, tile_b, n, 1), lambda g, b, j: (g, b, 0, 0)),
            pl.BlockSpec((1, tile_b, n, 1), lambda g, b, j: (g, b, 0, 0)),
            pl.BlockSpec((1, tile_b, n, 1), lambda g, b, j: (g, b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_b, tile_j, d), lambda g, b, j: (g, b, j, 0)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=48 * 1024 * 1024),
        interpret=interpret,
    )(levels_lm, levels_lm, g.astype(levels_lm.dtype), dq, m_, l_, dd_)

    return dlv


def _xla_reference(levels_lm, bu_lm, td_lm, *, side, radius, attend_self):
    """Plain-XLA recomputation of the fused op (used for the backward pass).
    Must match the kernel's math contract bit-for-bit at the op level."""
    from glom_tpu.ops.consensus import build_local_mask, consensus_attention

    L, B, n, d = levels_lm.shape
    levels = jnp.transpose(levels_lm, (1, 2, 0, 3))  # [B, n, L, d]
    mask = build_local_mask(side, radius)
    cons = consensus_attention(levels, attend_self=attend_self, local_mask=mask)
    cons_lm = jnp.transpose(cons, (2, 0, 1, 3))  # [L, B, n, d]
    td_full = jnp.concatenate(
        [td_lm[: L - 1], jnp.zeros_like(td_lm[:1])], axis=0
    )
    div = jnp.concatenate(
        [jnp.full((L - 1, 1, 1, 1), 4.0), jnp.full((1, 1, 1, 1), 3.0)]
    ).astype(jnp.float32)
    new = (
        levels_lm.astype(jnp.float32)
        + bu_lm.astype(jnp.float32)
        + td_full.astype(jnp.float32)
        + cons_lm.astype(jnp.float32)
    ) / div
    return new.astype(levels_lm.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret):
    return _forward(
        levels_lm, bu_lm, td_lm,
        side=side, radius=radius, attend_self=attend_self, interpret=interpret,
    )


def _fused_fwd(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret):
    out = _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret)
    return out, (levels_lm, bu_lm, td_lm)


def _fused_bwd(side, radius, attend_self, interpret, res, g):
    """Blockwise backward: the mean is linear (d bu = d td = dout/div) and
    the attention part runs in the two Pallas kernels above — the [n, n]
    matrix is never materialized in the backward either, so long-context
    TRAINING keeps O(n) memory (the dense-recompute VJP this replaces
    rebuilt the full similarity and undid that property)."""
    from glom_tpu.models.core import contribution_divisor  # lazy: no cycle

    levels_lm, bu_lm, td_lm = res
    L, B, n, d = levels_lm.shape
    # The dkv pass keeps TWO full levels rows resident in VMEM; past
    # _BWD_ROW_LIMIT per row (f32 at n=4096, bf16 at n=8192) the kernels
    # cannot fit (measured: f32/n=4096 overflows scoped VMEM at every tile
    # size) and the dense-recompute VJP — O(n^2) HBM but correct — takes
    # over.
    if n * d * levels_lm.dtype.itemsize > _BWD_ROW_LIMIT:
        _, vjp = jax.vjp(
            lambda lv, bu, td: _xla_reference(
                lv, bu, td, side=side, radius=radius, attend_self=attend_self
            ),
            levels_lm, bu_lm, td_lm,
        )
        return vjp(g)
    f32 = jnp.float32
    div = contribution_divisor(L, dtype=f32).reshape(L, 1, 1, 1)
    # The kernels take the RAW cotangent, apply the divisor in-kernel (from
    # the level grid index), and the dkv pass emits the COMPLETE dlv in the
    # levels dtype — no divided/partial-sum copies of g hit HBM.
    dlv = _consensus_update_bwd(
        levels_lm, g,
        side=side, radius=radius, attend_self=attend_self, interpret=interpret,
    )
    dmean = g.astype(f32) / div
    return dlv, dmean.astype(bu_lm.dtype), dmean[: L - 1].astype(td_lm.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_consensus_update(
    levels_lm: jnp.ndarray,
    bu_lm: jnp.ndarray,
    td_lm: jnp.ndarray,
    *,
    side: int,
    radius: float = 0.0,
    attend_self: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """new_levels = (levels + bu + pad(td) + consensus(levels)) / div, fused.

    levels_lm: [L, B, n, d] level-major; bu_lm: [L, B, n, d];
    td_lm: [L-1, B, n, d] (top level's zero contribution is implicit).
    Returns [L, B, n, d]. Falls back to the XLA composition off-TPU.
    """
    L, B, n, d = levels_lm.shape
    on_tpu = jax.devices()[0].platform == "tpu"
    supported = d % 128 == 0 and n % 8 == 0 and L >= 2
    if not supported or not (on_tpu or interpret):
        return _xla_reference(
            levels_lm, bu_lm, td_lm,
            side=side, radius=radius, attend_self=attend_self,
        )
    return _fused(levels_lm, bu_lm, td_lm, side, radius, attend_self, interpret)
