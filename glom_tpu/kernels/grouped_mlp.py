"""Pallas TPU kernel: fused per-group MLP (the grouped feed-forward hot op).

Profiling (see bench.py methodology) shows the per-iteration cost of the
scanned GLOM update is dominated by the two grouped FFWs; XLA materializes
the [.., G, 4d] hidden activations in HBM between the two matmuls. This
kernel computes  out = gelu(x @ w1 + b1) @ w2 + b2  per group with the
hidden tile resident in VMEM — HBM sees only x, the weights, and out.

Grid layout: (G, M_tiles) with the m axis innermost, so each group's weight
pair stays resident in VMEM across all of its row tiles (revisits cost
nothing; the next group triggers one weight DMA).

Backward: custom_vjp over ONE fully-fused Pallas kernel that emits dx and
accumulates all four weight/bias grads in-kernel (f32 accumulators on
constant-index output blocks across the inner m grid axis). On the bf16
training path the forward also saves the pre-activation so the backward
skips its recompute matmul (4 matmuls/tile; f32 keeps the 5-matmul
recompute form — see _fwd for the measured trade and
results/profiles/PROFILE.md for the history: the plain-XLA backward ran
the dw matmuls at 33% MFU off scan-residual fusions, the two-stage
kernel+einsum design fixed that, and folding dw/db+save-pre in-kernel
removed the [G, M, f] round trips entirely; 1955 -> ~3470
column-iters/s on v5e across those generations).

Falls back to the XLA einsum path (ops/ffw.py) off-TPU, under interpret
testing, and for shapes that don't tile cleanly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glom_tpu.ops.ffw import GroupedFFWParams, grouped_ffw, grouped_ffw_lm



def _erf(x):
    """Abramowitz & Stegun 7.1.26 rational approximation (max err 1.5e-7).
    The Pallas TPU lowering has no erf/erfc primitive; this uses only
    mul/add/exp, all VPU-native. 1.5e-7 is far below bf16 resolution and
    inside the f32 test tolerances."""
    sign = jnp.sign(x)
    x = jnp.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    poly = t * (
        0.254829592
        + t * (-0.284496736 + t * (1.421413741 + t * (-1.453152027 + t * 1.061405429)))
    )
    return sign * (1.0 - poly * jnp.exp(-x * x))


SQRT_2_OVER_PI = 0.7978845608028654
GELU_TANH_C = 0.044715


def _gelu_value_and_grad(z, *, tanh_approx, erf=_erf):
    """GELU value + derivative in f32, the single source of truth for every
    backward path (fused kernel and XLA fallback). tanh_approx selects the
    tanh form (matching the bf16 forward's activation); otherwise the exact
    erf form, with the erf implementation injectable (rational approx inside
    Pallas, jax.lax.erf in XLA). Callers needing only the value rely on DCE
    to drop the derivative."""
    if tanh_approx:
        u = SQRT_2_OVER_PI * (z + GELU_TANH_C * z * z * z)
        t = jnp.tanh(u)
        val = 0.5 * z * (1.0 + t)
        grad = 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * SQRT_2_OVER_PI * (
            1.0 + 3.0 * GELU_TANH_C * z * z
        )
    else:
        phi = jnp.exp(-0.5 * z * z) * (1.0 / jnp.sqrt(2.0 * jnp.pi))
        Phi = 0.5 * (1.0 + erf(z * 0.7071067811865476))
        val = z * Phi
        grad = Phi + z * phi
    return val, grad


def _gelu_exact(x):
    """Exact (erf-based) GELU, matching jax.nn.gelu(approximate=False)."""
    return _gelu_value_and_grad(x, tanh_approx=False)[0]


def _mlp_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, out_ref, *pre_ref):
    """One (group, row-tile) program: [TM, d] -> [TM, d] through the f-wide
    hidden layer entirely in VMEM.

    Activation precision: in bfloat16 compute the tanh GELU replaces the
    exact-erf one — their difference (<~1.1e-3 absolute) is below bf16
    resolution at GELU-scale activations, and the erf rational costs ~13%
    of the whole kernel on the VPU (measured 156 -> 179 TF/s). Float32
    compute keeps the exact erf so the f32 path stays bit-comparable to
    the reference contract.

    When a trailing `pre_ref` output is present (the training forward under
    custom_vjp), the pre-activation is also emitted (compute dtype) so the
    backward kernel can skip its recompute matmul — see _fwd for the trade.
    """
    x = x_ref[0]  # [TM, d]
    pre = jnp.dot(x, w1_ref[0], preferred_element_type=jnp.float32)
    pre = pre + b1_ref[0].astype(jnp.float32)  # b1_ref[0]: [1, f], broadcasts
    if pre_ref:
        pre_ref[0][0] = pre.astype(x.dtype)
    if x.dtype == jnp.bfloat16:
        h = jax.nn.gelu(pre, approximate=True)
    else:
        h = _gelu_exact(pre)
    h = h.astype(x.dtype)
    out = jnp.dot(h, w2_ref[0], preferred_element_type=jnp.float32)
    out = out + b2_ref[0].astype(jnp.float32)
    out_ref[0] = out.astype(out_ref.dtype)


def _tiled_add(x, a):
    """x [TM, d] + a [n, d] with TM % n == 0: the positional addend
    repeats every n rows (M = b*n with n inner), so the tile-local add is
    a reshape-broadcast — no materialized [G, M, d] sum ever hits HBM."""
    tm, d = x.shape
    n = a.shape[0]
    return (x.reshape(tm // n, n, d) + a[None]).reshape(tm, d)


def _mlp_kernel_add(x_ref, a_ref, w1_ref, b1_ref, w2_ref, b2_ref, out_ref,
                    *pre_ref):
    """_mlp_kernel with a positional addend folded into the input load:
    pre = (x + a)@w1 + b1. A trailing pre output is present only on the
    training forward (no-grad forwards skip the [G, M, f] HBM write);
    GELU form follows the dtype like _mlp_kernel."""
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    pre = jnp.dot(xa, w1_ref[0], preferred_element_type=jnp.float32)
    pre = pre + b1_ref[0].astype(jnp.float32)
    if pre_ref:
        pre_ref[0][0] = pre.astype(xa.dtype)
    if xa.dtype == jnp.bfloat16:
        h = jax.nn.gelu(pre, approximate=True)
    else:
        h = _gelu_exact(pre)
    h = h.astype(xa.dtype)
    out = jnp.dot(h, w2_ref[0], preferred_element_type=jnp.float32)
    out = out + b2_ref[0].astype(jnp.float32)
    out_ref[0] = out.astype(out_ref.dtype)


def _fused_forward(
    params: GroupedFFWParams,
    x: jnp.ndarray,
    *,
    tile_m: int,
    interpret: bool,
    save_pre: bool = False,
):
    """x: [G, M, d] -> [G, M, d] (group-major so every block keeps the
    tile-aligned [TM, d] trailing dims the TPU lowering requires).
    save_pre=True additionally returns the [G, M, f] pre-activation
    (compute dtype) for the backward."""
    G, M, d = x.shape
    f = params.w1.shape[-1]
    # m innermost: each group's weight pair stays VMEM-resident across all
    # of its row tiles.
    grid = (G, M // tile_m)
    out_shape = jax.ShapeDtypeStruct((G, M, d), x.dtype)
    out_spec = pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0))
    if save_pre:
        out_shape = (out_shape, jax.ShapeDtypeStruct((G, M, f), x.dtype))
        out_spec = (out_spec, pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)))
    return pl.pallas_call(
        _mlp_kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0)),  # x
            pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),  # w1
            # biases as [G, 1, f]: block dims equal to array dims satisfy the
            # TPU (8, 128)-tiling rule without padding
            pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),  # b1
            pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),  # w2
            pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),  # b2
        ],
        out_specs=out_spec,
        # The save_pre variant (training fwd) carries the extra [TM, f]
        # output block, and d>=1024 shapes carry 16MB+ of resident weights
        # — both overflow Mosaic's default 16MB scope (the d=1024/f=4096
        # pod shape needs 44MB); v5e has 128MB physical. Smaller inference
        # shapes keep the default budget (the measured-fast configuration).
        compiler_params=(
            pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)
            if save_pre or _fwd_ws(tile_m, d, f, x.dtype.itemsize) > 14 * 1024 * 1024
            else None
        ),
        interpret=interpret,
    )(x, params.w1, params.b1[:, None, :], params.w2, params.b2[:, None, :])


def _fused_forward_add(
    params: GroupedFFWParams,
    x: jnp.ndarray,
    a: jnp.ndarray,
    *,
    tile_m: int,
    interpret: bool,
    save_pre: bool = False,
):
    """Forward with the positional addend folded in-kernel. x [G, M, d],
    a [n, d] with tile_m % n == 0; save_pre only on the training path
    (a no-grad forward must not write the [G, M, f] pre to HBM)."""
    G, M, d = x.shape
    f = params.w1.shape[-1]
    grid = (G, M // tile_m)
    out_shape = jax.ShapeDtypeStruct((G, M, d), x.dtype)
    out_spec = pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0))
    if save_pre:
        out_shape = (out_shape, jax.ShapeDtypeStruct((G, M, f), x.dtype))
        out_spec = (out_spec, pl.BlockSpec((1, tile_m, f), lambda g, m: (g, m, 0)))
    return pl.pallas_call(
        _mlp_kernel_add,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_m, d), lambda g, m: (g, m, 0)),  # x
            pl.BlockSpec(a.shape, lambda g, m: (0, 0)),  # add (resident)
            pl.BlockSpec((1, d, f), lambda g, m: (g, 0, 0)),  # w1
            pl.BlockSpec((1, 1, f), lambda g, m: (g, 0, 0)),  # b1
            pl.BlockSpec((1, f, d), lambda g, m: (g, 0, 0)),  # w2
            pl.BlockSpec((1, 1, d), lambda g, m: (g, 0, 0)),  # b2
        ],
        out_specs=out_spec,
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x, a, params.w1, params.b1[:, None, :], params.w2, params.b2[:, None, :])


# Forward row tiles. 1024 overflowed the default scope in-scan when this was
# tuned and 512 remains the measured sweet spot; the save_pre variant raises
# vmem_limit_bytes for its extra output block, not to admit bigger tiles.
TILE_CANDIDATES = (512, 256, 128)

# Working-set budget per kernel program, under the 64MB scoped-vmem caps
# (v5e: 128MB physical, and the whole PROGRAM must co-schedule buffers,
# register-spill slots, and remat recompute — measured 131-144M > 128M at
# d=1024/f=4096 where the backward's resident f32 dw accumulators alone
# are 32M+32M). 48M sends that shape to the XLA backward while keeping
# the kernel at the flagship (24M @ tile 512) and at the pod's declared
# per-TP-rank f/mp=2048 (40M @ tile 512).
_WS_BUDGET = 48 * 1024 * 1024


def _fwd_ws(tile: int, d: int, f: int, itemsize: int) -> int:
    """Forward working set: resident weight pair + f32 pre scratch +
    2x-buffered x/out (+pre out on the save_pre path, counted always —
    it is the training configuration)."""
    weights = 2 * d * f * itemsize
    pre_scratch = tile * f * 4
    blocks = tile * d * itemsize * 2 * 2 + tile * f * itemsize * 2
    return weights + pre_scratch + blocks


def _bwd_ws(tile: int, d: int, f: int, itemsize: int) -> int:
    """Backward working set: weights + f32 dw accumulators (resident
    across the m axis) + f32 dpre + 2x-buffered x/g/pre-in/dx blocks."""
    weights = 2 * d * f * itemsize
    accums = 2 * d * f * 4 + (d + f) * 4
    dpre = tile * f * 4
    blocks = tile * (2 * d * itemsize * 2 + f * itemsize * 2 + d * itemsize * 2)
    return weights + accums + dpre + blocks


def _pick_tile(M: int, d: int = 512, f: int = 2048, itemsize: int = 2) -> int | None:
    """Largest MXU-friendly row tile dividing M whose forward working set
    fits the budget (None -> no clean tiling)."""
    for t in TILE_CANDIDATES:
        if M % t == 0 and _fwd_ws(t, d, f, itemsize) <= _WS_BUDGET:
            return t
    return None


def _supported(params: GroupedFFWParams, x: jnp.ndarray, tile_m: int | None) -> bool:
    if x.ndim < 3 or tile_m is None:
        return False
    f = params.w1.shape[-1]
    d = x.shape[-1]
    # Clean MXU tiling: row tiles divide M (via _pick_tile); d/f on 128-lane
    # boundaries.
    return d % 128 == 0 and f % 128 == 0


def _mlp_bwd_kernel(
    x_ref,      # [1, TM, d]
    w1_ref,     # [1, d, f]
    b1_ref,     # [1, 1, f]
    w2_ref,     # [1, f, d]
    g_ref,      # [1, TM, d]   upstream cotangent
    dx_ref,     # [1, TM, d]
    dw1_ref,    # [1, d, f]    f32 accumulator (index constant across m)
    db1_ref,    # [1, 1, f]    f32 accumulator
    dw2_ref,    # [1, f, d]    f32 accumulator
    db2_ref,    # [1, 1, d]    f32 accumulator
):
    """One (group, row-tile) program of the FULLY-fused backward: recompute
    the pre-activation in VMEM, apply the GELU derivative, emit dx, and
    accumulate ALL FOUR weight/bias grads in-kernel. The m axis is the
    inner grid dimension, so the f32 dw/db output blocks keep a constant
    block index across a group's row tiles — they live in VMEM as
    accumulators (single-buffered; ~8MB at d=512/f=2048) and flush to HBM
    once per group. Compared to the earlier two-stage design (kernel emits
    dpre/h, XLA einsums contract them), the [G, M, f] dpre/h tensors never
    touch HBM at all and the separate db reduction sweeps disappear —
    measured ~8% step-time win at the flagship config.

    The per-tile dw matmuls contract the TM row axis on the MXU (tile
    picked from BWD_TILE_CANDIDATES; 512 measured best — see the comment
    there); operands are downcast to the compute dtype exactly as the XLA
    einsum path's operands were, so the math is unchanged.

    GELU derivative matches the forward's per-dtype choice: tanh-GELU in
    bfloat16 (the fwd kernel's bf16 activation), exact erf in float32.
    """
    pre = jnp.dot(
        x_ref[0], w1_ref[0], preferred_element_type=jnp.float32
    ) + b1_ref[0].astype(jnp.float32)
    _mlp_bwd_tail(
        pre, x_ref[0], g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
    )


def _mlp_bwd_tail(pre, x, g, w1, w2, dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
                  inc=None):
    """Shared tail of both backward kernels (recompute and saved-pre): the
    dh/dx matmuls, the in-kernel dw/db accumulation, and the init/accum
    revisit logic. `pre` is f32 however the caller obtained it.

    inc: optional (dw1_in, db1_in, dw2_in, db2_in) refs of INCOMING f32
    accumulators (same block indices as the outputs) — the cross-iteration
    accumulation the hand-rolled loop VJP (kernels/fused_loop.py) chains
    through the backward instead of XLA add_any sweeps: the init-at-m==0
    branch seeds from the incoming value rather than zero."""
    f32 = jnp.float32
    m = pl.program_id(1)

    h32, dact = _gelu_value_and_grad(pre, tanh_approx=x.dtype == jnp.bfloat16)
    h = h32.astype(x.dtype)

    # dh = g @ w2^T  (contract the d axis of both)
    dh = jax.lax.dot_general(g, w2, (((1,), (1,)), ((), ())), preferred_element_type=f32)
    dpre = (dh * dact).astype(x.dtype)

    # dx = dpre @ w1^T (contract f)
    dx = jax.lax.dot_general(dpre, w1, (((1,), (1,)), ((), ())), preferred_element_type=f32)
    dx_ref[0] = dx.astype(dx_ref.dtype)
    dx32 = dx  # returned for the add-variant's da accumulation

    # Weight/bias grad contributions of this row tile (contract TM).
    dw1_step = jax.lax.dot_general(
        x, dpre, (((0,), (0,)), ((), ())), preferred_element_type=f32
    )  # [d, f]
    dw2_step = jax.lax.dot_general(
        h, g, (((0,), (0,)), ((), ())), preferred_element_type=f32
    )  # [f, d]
    db1_step = jnp.sum(dpre.astype(f32), axis=0, keepdims=True)  # [1, f]
    db2_step = jnp.sum(g.astype(f32), axis=0, keepdims=True)  # [1, d]

    @pl.when(m == 0)
    def _init():
        if inc is None:
            dw1_ref[0] = dw1_step
            db1_ref[0] = db1_step
            dw2_ref[0] = dw2_step
            db2_ref[0] = db2_step
        else:
            dw1_ref[0] = inc[0][0] + dw1_step
            db1_ref[0] = inc[1][0] + db1_step
            dw2_ref[0] = inc[2][0] + dw2_step
            db2_ref[0] = inc[3][0] + db2_step

    @pl.when(m != 0)
    def _accum():
        dw1_ref[0] += dw1_step
        db1_ref[0] += db1_step
        dw2_ref[0] += dw2_step
        db2_ref[0] += db2_step

    return dx32


def _mlp_bwd_kernel_saved(
    x_ref,      # [1, TM, d]
    w1_ref,     # [1, d, f]
    pre_ref,    # [1, TM, f]   pre-activation SAVED by the forward (compute
                #              dtype) — replaces the recompute matmul
    w2_ref,     # [1, f, d]
    g_ref,      # [1, TM, d]
    dx_ref,     # [1, TM, d]
    dw1_ref,    # [1, d, f]    f32 accumulators, as in _mlp_bwd_kernel
    db1_ref,    # [1, 1, f]
    dw2_ref,    # [1, f, d]
    db2_ref,    # [1, 1, d]
):
    """_mlp_bwd_kernel minus the pre-activation recompute: 4 matmuls per
    tile instead of 5. Used on the bf16 path where the forward saved pre
    (see _fwd for the measured trade); the GELU value/derivative are
    re-derived from the SAVED (rounded-to-bf16) pre, which differs from
    the recompute path by at most one bf16 ulp of pre — inside the bf16
    training tolerance."""
    _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), x_ref[0], g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
    )


def _mlp_bwd_kernel_saved_add(
    x_ref,      # [1, TM, d]   RAW x (addend NOT applied)
    a_ref,      # [n, d]       positional addend (resident)
    w1_ref,     # [1, d, f]
    pre_ref,    # [1, TM, f]   saved pre (already includes the addend)
    w2_ref,     # [1, f, d]
    g_ref,      # [1, TM, d]
    dx_ref,     # [1, TM, d]
    dw1_ref,    # [1, d, f]    f32 accumulators (constant index across m)
    db1_ref,    # [1, 1, f]
    dw2_ref,    # [1, f, d]
    db2_ref,    # [1, 1, d]
    da_ref,     # [n, d]       f32 accumulator, constant index across the
                #              WHOLE grid: da = sum over groups, batch
                #              copies, and tiles of dx
):
    """_mlp_bwd_kernel_saved for the folded positional addend: the dw1
    contraction uses xa = x + tile(a) (the true layer input), dx is the
    cotangent of BOTH x and (reduced) a — the da reduction rides the
    kernel instead of a separate XLA sweep."""
    xa = _tiled_add(x_ref[0], a_ref[...]).astype(x_ref.dtype)
    dx32 = _mlp_bwd_tail(
        pre_ref[0].astype(jnp.float32), xa, g_ref[0], w1_ref[0], w2_ref[0],
        dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
    )
    tm, d = dx32.shape
    n = a_ref.shape[0]
    da_step = jnp.sum(dx32.reshape(tm // n, n, d), axis=0)
    first = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

    @pl.when(first)
    def _init_da():
        da_ref[...] = da_step

    @pl.when(jnp.logical_not(first))
    def _accum_da():
        da_ref[...] += da_step


# Larger row tiles give the in-kernel dw matmuls a longer contraction axis;
# the raised vmem_limit_bytes scope makes them fit.
# 512 measured best on v5e at the flagship config (3227 col-iters/s vs 2907
# at 128 and 2975 at 1024 — long enough dw contraction without starving the
# pipeline); 1024 regresses despite fitting the raised budget.
BWD_TILE_CANDIDATES = (512, 256, 128)


def _pick_bwd_tile(
    M: int, d: int = 512, f: int = 2048, itemsize: int = 2
) -> int | None:
    for t in BWD_TILE_CANDIDATES:
        if M % t == 0 and _bwd_ws(t, d, f, itemsize) <= _WS_BUDGET:
            return t
    return None



def _bwd_compiler_params(tile_m: int, d: int, f: int, itemsize: int):
    """Scoped-VMEM grant for the backward kernels, shared by the plain and
    add-fold variants: the d=512-class resident set lands ~0.5MB over
    Mosaic's default 16MB scope; d=1024-class shapes (the pod's per-TP-rank
    f=2048) measure 75-78M of Mosaic stack at tile 512, so shapes past the
    32MB model estimate get the 100MB grant (v5e: 128MB physical)."""
    big = _bwd_ws(tile_m, d, f, itemsize) > 32 * 1024 * 1024
    return pltpu.CompilerParams(
        vmem_limit_bytes=(100 if big else 64) * 1024 * 1024
    )


def _fused_backward(params, x, g, *, tile_m: int, interpret: bool, pre=None):
    G, M, d = x.shape
    f = params.w1.shape[-1]
    f32 = jnp.float32
    grid = (G, M // tile_m)
    out_shapes = (
        jax.ShapeDtypeStruct((G, M, d), x.dtype),  # dx
        jax.ShapeDtypeStruct((G, d, f), f32),  # dw1
        jax.ShapeDtypeStruct((G, 1, f), f32),  # db1
        jax.ShapeDtypeStruct((G, f, d), f32),  # dw2
        jax.ShapeDtypeStruct((G, 1, d), f32),  # db2
    )
    if pre is not None:
        kernel = _mlp_bwd_kernel_saved
        second_in = pre
        second_spec = pl.BlockSpec((1, tile_m, f), lambda gi, m: (gi, m, 0))
    else:
        kernel = _mlp_bwd_kernel
        second_in = params.b1[:, None, :]
        second_spec = pl.BlockSpec((1, 1, f), lambda gi, m: (gi, 0, 0))
    dx, dw1, db1, dw2, db2 = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # x
            pl.BlockSpec((1, d, f), lambda gi, m: (gi, 0, 0)),  # w1
            second_spec,  # b1 (recompute) or saved pre
            pl.BlockSpec((1, f, d), lambda gi, m: (gi, 0, 0)),  # w2
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # g
        ],
        out_specs=(
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # dx
            pl.BlockSpec((1, d, f), lambda gi, m: (gi, 0, 0)),  # dw1
            pl.BlockSpec((1, 1, f), lambda gi, m: (gi, 0, 0)),  # db1
            pl.BlockSpec((1, f, d), lambda gi, m: (gi, 0, 0)),  # dw2
            pl.BlockSpec((1, 1, d), lambda gi, m: (gi, 0, 0)),  # db2
        ),
        compiler_params=_bwd_compiler_params(tile_m, d, f, x.dtype.itemsize),
        interpret=interpret,
    )(x, params.w1, second_in, params.w2, g)

    w1, b1, w2, b2 = params
    grads = GroupedFFWParams(
        dw1.astype(w1.dtype),
        db1[:, 0].astype(b1.dtype),
        dw2.astype(w2.dtype),
        db2[:, 0].astype(b2.dtype),
    )
    return grads, dx


def _fused_backward_add(params, x, a, pre, g, *, tile_m: int, interpret: bool):
    """_fused_backward for the folded-addend path (saved-pre form only):
    additionally emits da [n, d] accumulated in-kernel across the whole
    grid."""
    G, M, d = x.shape
    f = params.w1.shape[-1]
    f32 = jnp.float32
    n = a.shape[0]
    dx, dw1, db1, dw2, db2, da = pl.pallas_call(
        _mlp_bwd_kernel_saved_add,
        out_shape=(
            jax.ShapeDtypeStruct((G, M, d), x.dtype),  # dx
            jax.ShapeDtypeStruct((G, d, f), f32),  # dw1
            jax.ShapeDtypeStruct((G, 1, f), f32),  # db1
            jax.ShapeDtypeStruct((G, f, d), f32),  # dw2
            jax.ShapeDtypeStruct((G, 1, d), f32),  # db2
            jax.ShapeDtypeStruct((n, d), f32),  # da
        ),
        grid=(G, M // tile_m),
        in_specs=[
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # x
            pl.BlockSpec((n, d), lambda gi, m: (0, 0)),  # a (resident)
            pl.BlockSpec((1, d, f), lambda gi, m: (gi, 0, 0)),  # w1
            pl.BlockSpec((1, tile_m, f), lambda gi, m: (gi, m, 0)),  # pre
            pl.BlockSpec((1, f, d), lambda gi, m: (gi, 0, 0)),  # w2
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # g
        ],
        out_specs=(
            pl.BlockSpec((1, tile_m, d), lambda gi, m: (gi, m, 0)),  # dx
            pl.BlockSpec((1, d, f), lambda gi, m: (gi, 0, 0)),  # dw1
            pl.BlockSpec((1, 1, f), lambda gi, m: (gi, 0, 0)),  # db1
            pl.BlockSpec((1, f, d), lambda gi, m: (gi, 0, 0)),  # dw2
            pl.BlockSpec((1, 1, d), lambda gi, m: (gi, 0, 0)),  # db2
            pl.BlockSpec((n, d), lambda gi, m: (0, 0)),  # da (whole-grid acc)
        ),
        compiler_params=_bwd_compiler_params(tile_m, d, f, x.dtype.itemsize),
        interpret=interpret,
    )(x, a, params.w1, pre, params.w2, g)

    w1, b1, w2, b2 = params
    grads = GroupedFFWParams(
        dw1.astype(w1.dtype),
        db1[:, 0].astype(b1.dtype),
        dw2.astype(w2.dtype),
        db2[:, 0].astype(b2.dtype),
    )
    return grads, dx, da.astype(a.dtype)


def _weight_grads(params, x, dpre, h, g):
    """The four weight/bias grads shared by both backward paths: batched
    matmuls with f32 accumulation, results cast back to the param dtypes."""
    w1, b1, w2, b2 = params
    f32 = jnp.float32
    dw1 = jnp.einsum("gmd,gmf->gdf", x, dpre, preferred_element_type=f32)
    db1 = jnp.sum(dpre.astype(f32), axis=1)
    dw2 = jnp.einsum("gmf,gmd->gfd", h, g, preferred_element_type=f32)
    db2 = jnp.sum(g.astype(f32), axis=1)
    return GroupedFFWParams(
        dw1.astype(w1.dtype),
        db1.astype(b1.dtype),
        dw2.astype(w2.dtype),
        db2.astype(b2.dtype),
    )


def _xla_backward(params, x, g):
    """XLA fallback backward for shapes the bwd kernel can't tile. Still the
    VJP of the PALLAS forward, so the GELU derivative follows the same
    per-dtype choice as the fwd kernel (tanh in bf16, exact erf in f32)."""
    w1, b1, w2, b2 = params
    f32 = jnp.float32
    # Recompute the hidden pre-activation (one extra matmul) rather than
    # saving the [G, M, f] tensor — same memory/recompute trade as flash
    # attention's backward. EVERY contraction and reduction below pins
    # float32 accumulation (preferred_element_type / f32 dpre), matching the
    # forward paths' invariant — bf16 accumulation over f=4d or M=b*n terms
    # loses digits.
    pre = jnp.einsum("gmd,gdf->gmf", x, w1, preferred_element_type=f32)
    pre = pre + b1.astype(f32)[:, None, :]
    h32, dact = _gelu_value_and_grad(
        pre, tanh_approx=x.dtype == jnp.bfloat16, erf=jax.lax.erf
    )
    h = h32.astype(x.dtype)

    dh = jnp.einsum("gmd,gfd->gmf", g, w2, preferred_element_type=f32)
    dpre = (dh * dact).astype(x.dtype)

    dx = jnp.einsum("gmf,gdf->gmd", dpre, w1, preferred_element_type=f32)
    return _weight_grads(params, x, dpre, h, g), dx.astype(x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_lm(params, x, tile_m, interpret):
    """Level-major core: x [G, M, d] -> [G, M, d]. The layout the kernel
    wants natively — callers that keep a level-major carry pay zero
    transposes."""
    return _fused_forward(params, x, tile_m=tile_m, interpret=interpret)


# Per-call cap on the saved [G, M, f] pre-activation residual. Under a
# NON-remat scan the residual is stacked once per iteration, so an
# unconditional save at larger-than-flagship configs (d=1024-class) risks
# HBM exhaustion where the recompute form previously fit; the flagship
# bf16 config (~400MB/FFW call) stays under and keeps its measured win.
# Remat configs never stack (the body recomputes), so they are safe either
# way.
_SAVE_PRE_LIMIT = 512 * 1024 * 1024


def _save_pre_ok(params: GroupedFFWParams, x: jnp.ndarray) -> bool:
    """Single source of the save-pre eligibility (bf16, bwd-tileable,
    residual under the memory cap) — shared by the plain training forward
    and the folded-addend gate so the invariant cannot drift."""
    f = params.w1.shape[-1]
    save_bytes = x.shape[0] * x.shape[1] * f * x.dtype.itemsize
    return (
        x.dtype == jnp.bfloat16
        and _pick_bwd_tile(x.shape[1], x.shape[2], f, x.dtype.itemsize)
        is not None
        and save_bytes <= _SAVE_PRE_LIMIT
    )


def _fwd(params, x, tile_m, interpret):
    # bf16 training: ALSO save the pre-activation so the backward kernel
    # drops its recompute matmul (5 -> 4 per tile). The [G, M, f] bf16
    # round trip (~1.7 ms/step at the flagship config) costs less than the
    # ~3.5 ms of MXU recompute it replaces — the opposite verdict from the
    # PRE-merged-kernel measurement in results/profiles/PROFILE.md, because
    # back then the backward also emitted dpre/h and the extra output
    # overflowed VMEM at useful tiles. f32 keeps the recompute (saving f32
    # pre doubles the traffic and f32 runs are parity/testing paths).
    # Gated on _save_pre_ok so large non-remat configs keep recompute.
    if _save_pre_ok(params, x):
        out, pre = _fused_forward(
            params, x, tile_m=tile_m, interpret=interpret, save_pre=True
        )
        return out, (params, x, pre)
    return _fused_lm(params, x, tile_m, interpret), (params, x, None)


def _bwd(tile_m, interpret, res, g):
    params, x, pre = res  # x: [G, M, d]
    bt = _pick_bwd_tile(x.shape[1], x.shape[2], params.w1.shape[-1], x.dtype.itemsize)
    if bt is not None:
        return _fused_backward(params, x, g, tile_m=bt, interpret=interpret, pre=pre)
    # Inside a scan's backward, x arrives as a dynamic-slice of the stacked
    # residuals and the dw outputs feed the gradient-accumulation add; XLA
    # fuses both INTO the dw matmuls (select_add / slice fusions), dropping
    # them to ~33% MFU (profiled on v5e: 64 GF/s vs ~180 clean). The
    # barrier forces clean materialized operands so the einsums run as
    # plain matmuls at MXU rate.
    params, x, g = jax.lax.optimization_barrier((params, x, g))
    return _xla_backward(params, x, g)


_fused_lm.defvjp(_fwd, _bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_lm_add(params, x, a, tile_m, interpret):
    """Level-major core with a folded positional addend: equals
    _fused_lm(params, x + tile(a)) but the [G, M, d] sum never exists —
    the kernels add the [n, d] addend on tile load (forward AND backward),
    and da is reduced in-kernel. The primal (no-grad forward) skips the
    pre write; the training forward saves it (callers gate eligibility
    via _save_pre_ok)."""
    return _fused_forward_add(params, x, a, tile_m=tile_m, interpret=interpret)


def _fwd_add(params, x, a, tile_m, interpret):
    out, pre = _fused_forward_add(
        params, x, a, tile_m=tile_m, interpret=interpret, save_pre=True
    )
    return out, (params, x, a, pre)


def _bwd_add(tile_m, interpret, res, g):
    params, x, a, pre = res
    bt = _pick_bwd_tile(x.shape[1], x.shape[2], params.w1.shape[-1], x.dtype.itemsize)
    if bt is not None and bt % a.shape[0] == 0:
        return _fused_backward_add(
            params, x, a, pre, g, tile_m=bt, interpret=interpret
        )
    # Fallback (shouldn't trigger given the caller gate, but stays exact):
    # recompute xa in XLA and reduce da there.
    G, M, d = x.shape
    reps = M // a.shape[0]
    xa = x + jnp.tile(a, (reps, 1))[None]
    params_b, xa_b, g_b = jax.lax.optimization_barrier((params, xa, g))
    grads, dxa = _xla_backward(params_b, xa_b, g_b)
    da = jnp.sum(
        dxa.astype(jnp.float32).reshape(G, reps, a.shape[0], d), axis=(0, 1)
    )
    return grads, dxa, da.astype(a.dtype)


_fused_lm_add.defvjp(_fwd_add, _bwd_add)


_xla_lm = grouped_ffw_lm  # XLA fallback in level-major layout


def fused_grouped_ffw_lm(
    params: GroupedFFWParams,
    x: jnp.ndarray,
    *,
    add: jnp.ndarray | None = None,
    tile_m: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Level-major entry: x [G, M, d] -> [G, M, d] through the Pallas kernel
    (XLA einsum fallback off-TPU / unsupported shapes).

    add: optional [n, d] positional addend with M = b*n (n inner): the
    result equals fused_grouped_ffw_lm(params, x + tile(add)) but on the
    bf16 training path the add folds into the kernels' tile loads and the
    [G, M, d] sum never touches HBM (~2 ms/step at the flagship config).
    Unsupported shapes/dtypes fall back to the explicit add."""
    G, M, d = x.shape
    if tile_m is None:
        tile_m = _pick_tile(M, d, params.w1.shape[-1], x.dtype.itemsize)
    elif M % tile_m != 0:
        tile_m = None
    on_tpu = jax.devices()[0].platform == "tpu"
    kernel_ok = _supported(params, x, tile_m) and (on_tpu or interpret)
    if add is not None:
        n = add.shape[0]
        f = params.w1.shape[-1]
        bt = (
            _pick_bwd_tile(M, d, f, x.dtype.itemsize) if kernel_ok else None
        )
        # The add-backward keeps two extra residents the generic _bwd_ws
        # model doesn't count: the [n, d] addend block and the whole-grid
        # f32 da accumulator.
        add_extra = n * d * (x.dtype.itemsize + 4)
        fold = (
            kernel_ok
            # bf16 is the production fold; f32 folds only under interpret
            # (CI coverage of the add kernels — f32 save-pre stays off the
            # hardware path, same verdict as the plain save-pre gate).
            and (_save_pre_ok(params, x) or (interpret and x.dtype == jnp.float32))
            # No dtype-promotion surprise: the fold computes in x.dtype,
            # so only take it when the explicit x + add would too.
            and jnp.result_type(x.dtype, add.dtype) == x.dtype
            and M % n == 0
            and tile_m % n == 0
            and bt is not None
            and bt % n == 0
            and _bwd_ws(bt, d, f, x.dtype.itemsize) + add_extra <= _WS_BUDGET
        )
        if fold:
            return _fused_lm_add(params, x, add.astype(x.dtype), tile_m, interpret)
        # Fallback preserves jnp promotion semantics (e.g. f32 pos_emb +
        # bf16 carry promotes to f32, exactly like the explicit add did).
        x = x + jnp.tile(add, (M // n, 1))[None]
    if not kernel_ok:
        return _xla_lm(params, x)
    return _fused_lm(params, x, tile_m, interpret)


def fused_grouped_ffw(
    params: GroupedFFWParams,
    x: jnp.ndarray,
    *,
    tile_m: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for ops.ffw.grouped_ffw ([..., G, d] -> [..., G, d]).

    Uses the Pallas kernel on TPU (or anywhere under interpret=True); falls
    back to the XLA einsum path otherwise. tile_m=None picks the largest
    clean row tile automatically (e.g. 256 at batch=1/n=256), capped at
    512 by VMEM (TILE_CANDIDATES). Transposes to/from level-major around
    the kernel; hot
    loops should prefer fused_grouped_ffw_lm and keep the carry level-major.
    """
    M = 1
    for s in x.shape[:-2]:
        M *= s
    if tile_m is None:
        tile_m = _pick_tile(M, x.shape[-1], params.w1.shape[-1], x.dtype.itemsize)
    elif M % tile_m != 0:
        tile_m = None
    on_tpu = jax.devices()[0].platform == "tpu"
    if not _supported(params, x, tile_m) or not (on_tpu or interpret):
        return grouped_ffw(params, x)
    *lead, G, d = x.shape
    x2 = jnp.moveaxis(x.reshape(-1, G, d), 1, 0)  # [G, M, d]
    out = _fused_lm(params, x2, tile_m, interpret)
    return jnp.moveaxis(out, 0, 1).reshape(*lead, G, d)
