"""Loss-curve parity harness: glom_tpu vs the PyTorch oracle.

The BASELINE.json north star is "match the PyTorch-CUDA reference loss
curve". The reference publishes no curve (BASELINE.md), so this harness
produces the comparison from both directions itself. `--config` selects
the scale: `cifar10` (BASELINE config 2 — cheap enough for a 100-step
curve, the default) or `imagenet224` (the north-star L=6/d=512 config —
few steps at small batch; the torch side is ~15 s/step on CPU). Three
runs per invocation:

  * torch     — tests/oracle_torch.py (independent from-spec implementation,
                torch autograd + torch.optim.Adam), CPU fp32;
  * jax_f32   — glom_tpu with float32 + jax.default_matmul_precision
                ("highest") so TPU matmuls are true fp32 (the default TPU
                precision does bf16 passes, which would blur the comparison);
  * jax_bf16  — the production path (bf16 compute + Pallas kernels), to show
                the practical training curve tracks the fp32 one.

All three start from IDENTICAL weights and see IDENTICAL images and noise
(pre-generated on host). Writes one JSONL record per step with the three
losses and diffs, plus a summary line, to
results/loss_parity_torch[_<config>].jsonl.

Expectation, stated up front: jax_f32 matches torch to fp32 tolerance for
the early steps and stays within a small relative band thereafter (the
T-iteration column dynamics amplify last-bit differences over hundreds of
Adam steps — bit-identical curves across frameworks are not a meaningful
target; envelope agreement is).
"""

import argparse
import json

import numpy as np


CONFIGS = {
    # BASELINE config 2 scale — cheap enough for a 100-step curve.
    "cifar10": dict(dim=256, levels=5, image_size=32, patch_size=4),
    # The north-star config ("match the PyTorch loss curve on ImageNet-224,
    # L=6, d=512") — the torch side runs ~15 s/step on CPU, so use few
    # steps at small batch.
    "imagenet224": dict(dim=512, levels=6, image_size=224, patch_size=14),
}


def main(steps: int, batch: int, out_path: str, config: str = "cifar10"):
    import jax
    import jax.numpy as jnp
    import optax
    import torch

    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    import oracle_torch

    from glom_tpu.data import shapes_dataset
    from glom_tpu.train.objectives import denoise_loss, init_denoise
    from glom_tpu.utils.config import GlomConfig
    from glom_tpu.utils.metrics import detect_chip

    cfg = GlomConfig(**CONFIGS[config])
    lr, noise_std = 3e-4, 0.5
    chip = detect_chip()

    # Identical data + noise for every framework, pre-generated on host.
    data = shapes_dataset(batch, cfg.image_size, seed=11)
    rng = np.random.default_rng(12)
    shape = (batch, 3, cfg.image_size, cfg.image_size)
    images = [np.asarray(next(data), np.float32) for _ in range(steps)]
    noises = [
        (noise_std * rng.normal(size=shape)).astype(np.float32)
        for _ in range(steps)
    ]

    # Identical initial weights.
    params0 = init_denoise(jax.random.PRNGKey(42), cfg)
    tparams = oracle_torch.params_from_jax(params0)

    print(f"torch side: {steps} steps on CPU fp32 ...")
    torch.manual_seed(0)
    torch_losses = oracle_torch.train(tparams, images, noises, cfg, lr)

    def run_jax(compute_dtype, use_pallas, precision):
        opt = optax.adam(lr)

        def step_fn(params, opt_state, img, noise):
            with jax.default_matmul_precision(precision):
                loss, grads = jax.value_and_grad(denoise_loss)(
                    params, img, noise, cfg,
                    compute_dtype=compute_dtype, use_pallas=use_pallas,
                )
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        step_jit = jax.jit(step_fn)
        params, opt_state = params0, opt.init(params0)
        losses = []
        for img, noise in zip(images, noises):
            params, opt_state, loss = step_jit(
                params, opt_state, jnp.asarray(img), jnp.asarray(noise)
            )
            losses.append(float(loss))
        return losses

    print(f"jax_f32 side: {steps} steps on {chip} (matmul precision=highest) ...")
    jax_f32 = run_jax(None, False, "highest")
    print(f"jax_bf16 side: {steps} steps on {chip} (production path) ...")
    jax_bf16 = run_jax(jnp.bfloat16, chip != "cpu", "default")

    with open(out_path, "w") as f:
        max_rel = 0.0
        for i, (lt, lj, lb) in enumerate(zip(torch_losses, jax_f32, jax_bf16)):
            rel = abs(lj - lt) / max(abs(lt), 1e-12)
            max_rel = max(max_rel, rel)
            rec = {
                "step": i,
                "loss_torch": round(lt, 8),
                "loss_jax_f32": round(lj, 8),
                "loss_jax_bf16": round(lb, 8),
                "rel_diff_f32_vs_torch": round(rel, 8),
            }
            f.write(json.dumps(rec) + "\n")
        summary = {
            "summary": True,
            "config": config,
            "steps": steps,
            "batch": batch,
            "chip": chip,
            "final_loss_torch": round(torch_losses[-1], 6),
            "final_loss_jax_f32": round(jax_f32[-1], 6),
            "final_loss_jax_bf16": round(jax_bf16[-1], 6),
            "max_rel_diff_f32_vs_torch": round(max_rel, 8),
            "rel_diff_first10_max": round(
                max(
                    abs(a - b) / max(abs(b), 1e-12)
                    for a, b in zip(jax_f32[:10], torch_losses[:10])
                ),
                8,
            ),
        }
        f.write(json.dumps(summary) + "\n")
    print(json.dumps(summary))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--config", choices=sorted(CONFIGS), default="cifar10")
    # Default output varies with config so an imagenet224 run cannot
    # silently clobber the committed cifar10 artifact.
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    out = args.out or (
        "results/loss_parity_torch.jsonl"
        if args.config == "cifar10"
        else f"results/loss_parity_torch_{args.config}.jsonl"
    )
    main(args.steps, args.batch, out, args.config)
