"""Ring-vs-Ulysses crossover probe: the single-chip-measurable component.

docs/PARALLELISM.md claims Ulysses wins when L >= seq and n/seq is too
small to feed the MXU. With one physical chip, the COMM side (ring's
seq-1 ppermute hops vs Ulysses' one-shot all_to_all — both O(n*d*L/seq)
volume) cannot be measured; what CAN be measured is the COMPUTE-SHAPE
side of the claim, which is the mechanism behind it:

  * ring: each device runs seq sequential attention steps over
    [n/seq x n/seq] similarity chunks per level (L-batched small matmuls
    + seq-1 online-softmax combine passes);
  * ulysses: one dense attention over the FULL [n x n] similarity for
    L/seq levels (big matmuls, one softmax).

Total device FLOPs are identical (2 * n^2/seq * L * d per einsum either
way); the difference is pure matmul granularity + online-softmax
overhead — measured here per (n, seq, L) on the real chip, bf16, B=1.
Appends schema-stamped JSONL rows (kind "bench", watchdog backend state
riding every row via bench_bootstrap) to results/sp_crossover.jsonl.
"""

import json

import jax
import jax.numpy as jnp
from jax import lax

from glom_tpu.ops.consensus import consensus_attention
from glom_tpu.telemetry.sinks import emit
from glom_tpu.utils.helpers import l2norm
from glom_tpu.utils.metrics import detect_chip
from glom_tpu.utils.timing import calibrated_chain_time


def ring_compute(levels_full, n_loc, seq):
    """The per-device compute of one ring consensus pass, comms elided:
    queries = this shard's n_loc rows; k/v chunks arrive over `seq` steps
    (here: sliced from the resident full array — same matmul shapes and
    online-softmax combine as ring.py, zero ppermute)."""
    b, n, L, d = levels_full.shape
    q = levels_full[:, :n_loc]  # this shard's query band
    scale = d ** -0.5

    def step(s, carry):
        m, l, acc = carry
        kv = lax.dynamic_slice_in_dim(levels_full, s * n_loc, n_loc, axis=1)
        k = l2norm(kv)
        sim = jnp.einsum("bild,bjld->blij", q, k) * scale
        m_new = jnp.maximum(m, jnp.max(sim, axis=-1, keepdims=True))
        p = jnp.exp(sim - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("blij,bjld->bild", p.astype(levels_full.dtype), kv)
        acc_new = acc * corr.transpose(0, 2, 1, 3) + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((b, L, n_loc, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, L, n_loc, 1), jnp.float32)
    a0 = jnp.zeros((b, n_loc, L, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, seq, step, (m0, l0, a0))
    return acc / l.transpose(0, 2, 1, 3)


def main():
    chip = detect_chip()
    on_tpu = chip != "cpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    # (side, L, seq, d, B). Beyond the original d=512/L=8/B=1 grid, the
    # shapes the auto-selector actually GOVERNS (round-4 missing #4 — the
    # threshold must not be an extrapolation): the pod preset's
    # d=1024/L=12, the imagenet64-local L=6 class, and BATCHED rows (the
    # selector's working-set model claims b multiplies instance count on
    # both sides without moving the per-instance spill point — these rows
    # are that claim's check), at n spanning the modeled crossover
    # (runtime.ulysses_preferred: per-instance sim working set n^2*4 vs
    # VMEM).
    cases = (
        [(16, 8, s, 512, 1) for s in (2, 4, 8)]  # n=256: small-n/seq regime
        + [(32, 8, s, 512, 1) for s in (2, 4, 8)]  # n=1024
        + [(64, 8, s, 512, 1) for s in (2, 4)]  # n=4096: MXU fed either way
        + [(16, 12, s, 1024, 1) for s in (2, 4)]  # pod shape, n=256
        + [(32, 12, s, 1024, 1) for s in (2, 4)]  # pod shape, n=1024
        + [(64, 12, 2, 1024, 1)]                  # pod shape, n=4096
        + [(16, 6, 2, 512, 1), (32, 6, 2, 512, 1), (64, 6, 2, 512, 1)]
        + [(32, 8, 2, 512, 8), (64, 8, 2, 512, 8)]  # batched: b-independence
    ) if on_tpu else [(8, 4, 2, 64, 1)]

    for side, L, seq, d, B in cases:
        n = side * side
        levels = jax.random.normal(
            jax.random.PRNGKey(side + seq), (B, n, L, d), dtype
        )

        def ring_chain(k, _lv=levels, _s=seq, _nl=n // seq):
            def body(i, acc):
                out = ring_compute(_lv + acc.astype(_lv.dtype), _nl, _s)
                return jnp.sum(out).astype(jnp.float32) * 1e-9
            return lax.fori_loop(0, k, body, jnp.float32(0.0))

        def uly_chain(k, _lv=levels[:, :, : max(L // seq, 1)], _n=n):
            # ulysses local compute: full n, L/seq levels, dense
            def body(i, acc):
                out = consensus_attention(
                    _lv + acc.astype(_lv.dtype), attend_self=False
                )
                return jnp.sum(out).astype(jnp.float32) * 1e-9
            return lax.fori_loop(0, k, body, jnp.float32(0.0))

        # target_s=2.5: the fastest cases here are ~5 us/op, where a 0.5 s
        # chain leaves (t_chain - rtt) within the ~100 ms tunnel-RTT jitter
        # (observed as degenerate timings); a longer chain amortizes it.
        t_ring = calibrated_chain_time(
            jax.jit(ring_chain), levels, repeats=4, calib_k=8, target_s=2.5
        )
        t_uly = calibrated_chain_time(
            jax.jit(uly_chain), levels, repeats=4, calib_k=8, target_s=2.5
        )
        rec = {
            "metric": (
                f"sp_crossover ulysses_speedup (n={n}, L={L}, seq={seq}, "
                f"d={d}, B={B}, {chip})"
            ),
            "value": round(t_ring / t_uly, 3),
            "unit": "x",
            "n": n, "L": L, "seq": seq, "d": d, "B": B,
            "ring_compute_ms": round(t_ring * 1e3, 4),
            "ulysses_compute_ms": round(t_uly * 1e3, 4),
            "ulysses_speedup": round(t_ring / t_uly, 3),
            "chip": chip,
        }
        stamped = emit(rec)
        if on_tpu:
            with open("results/sp_crossover.jsonl", "a") as f:
                f.write(json.dumps(stamped) + "\n")


if __name__ == "__main__":
    import argparse

    from glom_tpu.telemetry.sinks import bench_bootstrap

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="capture an XProf trace of the measured chains into DIR",
    )
    args = ap.parse_args()
    if not bench_bootstrap("sp_crossover ulysses_speedup", "x"):
        raise SystemExit(0)
    if args.trace_dir:
        from glom_tpu.tracing.capture import trace

        with trace(args.trace_dir):
            main()
        emit({"note": "xla-trace captured", "trace_dir": args.trace_dir},
             kind="note")
    else:
        main()
