"""Training-step benchmark: fwd+bwd+optimizer MFU, and the loss-curve run.

The north star (BASELINE.md) is a TRAINING target — "match the PyTorch-CUDA
loss curve ... at >=70% MFU" — so forward-only numbers (bench.py) are not
enough. This harness:

  * default: times the full jitted train step (denoise loss, value_and_grad,
    adam update) at the flagship ImageNet-224 / L=6 / d=512 config in bf16
    with the fused Pallas forward, and prints ONE JSON line with
    column-iters/s/chip and MFU (backward counted as 2x forward FLOPs).
  * --loss-curve N: runs the CIFAR-scale config (BASELINE config 2) for N
    steps on the shapes dataset and appends JSONL records (step, loss,
    grad_norm, steps/sec, MFU) to results/cifar10_loss_curve.jsonl — the
    self-established loss-curve baseline the reference never published.

Timing methodology matches bench.py: K train steps chained inside one
compiled fori_loop (the optimizer state carry serializes them), synced by
fetching the final device-side loss scalar (block_until_ready is a no-op on
the tunneled platform), per-step time = (t_chain - t_rtt) / K with ONE long
chain and the tunnel RTT measured by fetching a trivial jitted scalar (see
glom_tpu/utils/timing.py for why the earlier two-chain slope was rejected:
clock-ramp differences between chains let it over-credit past the physical
peak).
"""

import argparse
import dataclasses
import jax
import jax.numpy as jnp

from glom_tpu.telemetry.sinks import emit
from glom_tpu.train.trainer import create_train_state, make_train_step
from glom_tpu.utils.config import GlomConfig, TrainConfig
from glom_tpu.utils.metrics import detect_chip, mfu
from glom_tpu.utils.timing import (
    best_fetch_time,
    calibrated_chain_time,
    measure_rtt,
)


def _train_iters(cfg: GlomConfig, tcfg: TrainConfig) -> int:
    """Scan iterations the train step actually executes: the loss reads the
    top level at recon_index, so iterations past it are dead code."""
    T = tcfg.iters if tcfg.iters is not None else cfg.default_iters
    return tcfg.recon_iter_index if tcfg.recon_iter_index is not None else T // 2 + 1


def bench_preset_train_step(preset_name: str, batch_override=None,
                            mult_override=None):
    """Single-chip train-step measurement at an arbitrary preset's MODEL
    shape (e.g. imagenet224-pod: L=12/d=1024/bf16/remat) — the per-chip
    anchor the analytic pod scaling model (docs/PARALLELISM.md) multiplies
    out. Chain length auto-calibrates (per-step cost varies by config).

    mult_override shrinks the FFW expansion: --mult 2 at the pod preset
    runs the PER-TP-RANK FFW shard shape (f/mp = 2048 at the declared
    model=2), where the working-set gate keeps the fused backward kernels
    ON — the shape a pod chip actually executes, vs the full-f single-chip
    shape that falls back to the XLA backward (the conservative anchor)."""
    from glom_tpu.utils.presets import get_preset

    chip = detect_chip()
    on_tpu = chip != "cpu"
    p = get_preset(preset_name)
    cfg = p.model
    if mult_override is not None:
        cfg = dataclasses.replace(cfg, mult=mult_override)
    batch = batch_override or (16 if on_tpu else 2)
    tcfg = dataclasses.replace(
        p.train,
        batch_size=batch,
        compute_dtype=p.train.compute_dtype if on_tpu else "float32",
        use_pallas=p.train.use_pallas and on_tpu,
    )
    k_iters = _train_iters(cfg, tcfg)

    state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    # Sustained-throughput step: grad-norm is observability, computed only
    # on logging iterations by the fit loops.
    step_fn = make_train_step(cfg, tcfg, optimizer, with_grad_norm=False)
    img = jax.device_put(
        jax.random.normal(
            jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size),
            jnp.float32,
        )
    )
    base_rng = jax.random.PRNGKey(2)

    # state/img ride as ARGUMENTS, not jit-closure constants: closed-over
    # arrays embed in the serialized MLIR, and at this config's ~2.3GB of
    # params+opt-state the remote-compile payload reliably breaks the
    # tunnel (broken pipe mid-POST).
    def multi(state_, img_, k):
        def body(i, carry):
            st, _ = carry
            st, metrics = step_fn(st, img_, jax.random.fold_in(base_rng, i))
            return st, metrics["loss"]

        _, loss = jax.lax.fori_loop(
            0, k, body, (state_, jnp.zeros((), jnp.float32))
        )
        return loss

    multi_jit = jax.jit(multi)
    per_step = calibrated_chain_time(
        lambda k: multi_jit(state, img, k), img,
        repeats=3 if on_tpu else 2, calib_k=3, target_s=2.0,
    )
    cips = batch * k_iters / per_step
    measured_mfu = mfu(cfg, cips, chip=chip, backward=True)
    emit(
        {
            "metric": (
                f"train_step column_iters_per_sec_per_chip ({preset_name}"
                f" single-chip: L={cfg.levels}, d={cfg.dim}, "
                f"f={cfg.dim * cfg.mult}, "
                f"batch={batch}, {tcfg.compute_dtype}"
                f"{', remat' if tcfg.remat else ''}"
                f"{', pallas' if tcfg.use_pallas else ''}, {chip})"
            ),
            "value": round(cips, 2),
            "unit": "column-iters/s/chip",
            "vs_baseline": round(measured_mfu / 0.70, 4),
        }
    )


def bench_train_step(batch_override=None):
    chip = detect_chip()
    on_tpu = chip != "cpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        # Batch 64 stays the official point. Round-4 curve
        # (results/batch_curve.jsonl): 3841 / 4183 / 4255 / 4306 / 3489 at
        # 16 / 32 / 64 / 96 / 128 — batch 96 measures ~1% above 64 (inside
        # the ~3% run-to-run band, i.e. statistically level). Round 5:
        # batch 128 no longer ships the 3489 scan-path regime —
        # make_train_step auto-routes it through grad_accum=2 over
        # batch-64 fused-loop microbatches (resolve_training_route); the
        # 128 row needs re-measurement on the automatic path.
        batch, repeats = batch_override or 64, 6
        # ~122 ms/step: k=9 gives ~1.1 s of device work per call, so the
        # ~100 ms tunnel RTT (measured and subtracted) bounds the error
        # at ~2%.
        k_chain = 9
    else:
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch, repeats = 4, 2
        k_chain = 3

    tcfg = TrainConfig(
        batch_size=batch,
        learning_rate=3e-4,
        compute_dtype="bfloat16" if on_tpu else "float32",
        use_pallas=on_tpu,
        # Unrolling the 7 executed iterations removes the scan-autodiff
        # residual-stack bookkeeping: ~3-5% step time, measured back-to-back.
        scan_unroll=on_tpu,
    )
    k_iters = _train_iters(cfg, tcfg)

    state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    # The sustained-throughput step (no grad-norm sweep): what fit runs on
    # every non-logging iteration.
    step_fn = make_train_step(cfg, tcfg, optimizer, with_grad_norm=False)
    img = jax.random.normal(
        jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size), jnp.float32
    )
    base_rng = jax.random.PRNGKey(2)

    def make_chain(k):
        def multi(state, x):
            def body(i, carry):
                st, _ = carry
                st, metrics = step_fn(st, x, jax.random.fold_in(base_rng, i))
                return st, metrics["loss"]
            _, loss = jax.lax.fori_loop(
                0, k, body, (state, jnp.zeros((), jnp.float32))
            )
            return loss
        return jax.jit(multi)

    t_rtt = measure_rtt(img, repeats=repeats)
    t_chain = best_fetch_time(make_chain(k_chain), state, img, repeats=repeats)
    per_step = (t_chain - t_rtt) / k_chain
    if per_step <= 0:
        raise RuntimeError(
            f"degenerate timing: t_chain={t_chain:.4f}s t_rtt={t_rtt:.4f}s"
        )

    column_iters_per_sec = batch * k_iters / per_step
    measured_mfu = mfu(cfg, column_iters_per_sec, chip=chip, backward=True)

    # Static per-replica live-bytes for the benched state, plus the ZeRO
    # comm model at the flagship dp=8 topology this single-chip number
    # anchors (pure analytics — identical with or without a chip): the
    # allreduce-vs-(reduce-scatter + all-gather) wire bytes the dp8 run
    # would move per step at zero_stage 0 vs 1.
    from glom_tpu.utils.metrics import comm_volume_model, live_bytes_model

    mem = live_bytes_model(
        state.params, state.opt_state, axis_sizes={},
        param_specs=None, opt_specs=None, grad_specs=None,
    )
    wire = mem["params_bytes_per_replica"]
    emit(
        {
            "metric": (
                f"train_step column_iters_per_sec_per_chip (ImageNet-224, "
                f"L=6, d=512, bf16 fwd+bwd+adam, pallas, {chip})"
                if on_tpu
                else "train_step column_iters_per_sec_per_chip "
                "(cpu-fallback cfg)"
            ),
            "value": round(column_iters_per_sec, 2),
            "unit": "column-iters/s/chip",
            "vs_baseline": round(measured_mfu / 0.70, 4),
            # the backward this number actually priced (round-4 weak
            # #3: a record must name its regime) — e.g. batch 128
            # reports fused_loop/2 via the auto-routing, not the
            # 0.96x scan path it used to silently measure
            "vjp_path": step_fn.vjp_path,
            "grad_accum": step_fn.grad_accum,
            "zero_stage": 0,  # single chip: dp=1 resolves to 0
            **mem,
            "comm_dp8_zero0_bytes_per_step": comm_volume_model(
                wire, wire, 8, 0
            )["comm_bytes_per_step"],
            "comm_dp8_zero1_bytes_per_step": comm_volume_model(
                wire, wire, 8, 1
            )["comm_bytes_per_step"],
        }
    )


def bench_telemetry_overhead(num_steps: int = 8, repeats: int = 4):
    """The telemetry A/B (acceptance bar: < 2% per-step at "scalars"):
    time the jitted train step with telemetry off vs scalars on the SAME
    config (CIFAR-scale on CPU, flagship on TPU) and emit one JSON line
    with the overhead. The scalars bundle is two extra tree reductions +
    one isfinite + the where() guard, all fused into the step — this
    bench is what keeps that claim measured, not assumed.

    Methodology: both arms compile up front, then repeats INTERLEAVE
    (off/scalars alternating, order flipped per repeat) with min per arm —
    sequential arms on a multi-tenant host confound the A/B with clock
    drift (measured: the same pair read +24% sequential vs +1.3%
    interleaved on a drifting CPU box; only the interleaved number
    reproduces the hand-isolated component costs)."""
    import time

    chip = detect_chip()
    on_tpu = chip != "cpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        batch = 32
    else:
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch = 8
    img = jax.random.normal(
        jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size),
        jnp.float32,
    )
    base_rng = jax.random.PRNGKey(2)
    steps, states = {}, {}
    for level in ("off", "scalars"):
        tcfg = TrainConfig(
            batch_size=batch,
            learning_rate=1e-3,
            compute_dtype="bfloat16" if on_tpu else "float32",
            use_pallas=on_tpu,
            telemetry_level=level,
        )
        state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        # The sustained-throughput variant: what fit runs between logs —
        # exactly where telemetry overhead would hurt.
        step = jax.jit(
            make_train_step(cfg, tcfg, optimizer, with_grad_norm=False),
            donate_argnums=(0,),
        )
        state, m = step(state, img, jax.random.fold_in(base_rng, 0))
        jax.block_until_ready(m["loss"])
        steps[level], states[level] = step, state
    times = {"off": float("inf"), "scalars": float("inf")}
    for rep in range(repeats):
        order = ("off", "scalars") if rep % 2 == 0 else ("scalars", "off")
        for level in order:
            step, state = steps[level], states[level]
            t0 = time.perf_counter()
            for i in range(num_steps):
                state, m = step(state, img, jax.random.fold_in(base_rng, i))
            jax.block_until_ready(m["loss"])
            times[level] = min(
                times[level], (time.perf_counter() - t0) / num_steps
            )
            states[level] = state
    overhead = times["scalars"] / times["off"] - 1.0
    emit(
        {
            "metric": f"telemetry_scalars_overhead (train_step A/B, {chip})",
            "value": round(overhead * 100, 3),
            "unit": "percent",
            "step_time_off_s": round(times["off"], 6),
            "step_time_scalars_s": round(times["scalars"], 6),
            "budget_pct": 2.0,
            "within_budget": bool(overhead < 0.02),
        }
    )


def bench_collective_timing_overhead(
    num_steps: int = 20, repeats: int = 3, interval: int = 10,
    log_every: int = 10,
):
    """The collective-timing overhead measurement (acceptance bar: < 2%
    per-step at "sampled"): the sampled mode changes NOTHING inside the
    compiled step (off and sampled lower the identical program; the
    harness runs outside jit), so its entire cost is one per-site
    re-dispatch pass every `log_every x interval` steps. Following the
    span-ab precedent, that cost is measured DIRECTLY — sample() wall
    clock vs step wall clock, amortized at the deployed cadence — rather
    than as a two-loop A/B, which on a multi-tenant host measures clock
    drift, not the harness (the same pair read 10-25% loop-to-loop on a
    drifting CPU box with ZERO ticks in either loop). Full mode is priced
    separately: it is a per-execution visibility mode, not a production
    default.

    The measured collective_time rows (with the α-β comm_time_model fit)
    are ALSO emitted — on a real TPU window this doubles as the model's
    re-fit measurement (run_hw_queue step 9j).

    Topology: dp = all visible devices when >= 2; otherwise a virtual
    8-device CPU mesh, labelled — the bench_zero convention (real
    collectives, meaningless absolute times, load-bearing RATIO)."""
    import json
    import os
    import time

    from glom_tpu.telemetry.watchdog import backend_record

    n = backend_record().get("backend_devices")
    if n is None or n < 2:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        )
        fallback = True
    else:
        fallback = False
    import jax as _jax  # backend init AFTER the platform decision

    from glom_tpu.parallel.runtime import DistributedTrainer
    from glom_tpu.utils.config import MeshConfig

    chip = detect_chip()
    dp = len(_jax.devices())
    cfg = GlomConfig(dim=32, levels=3, image_size=16, patch_size=4)
    rng = jax.random.PRNGKey(1)
    batch = jax.device_get(
        jax.random.normal(rng, (dp, 3, cfg.image_size, cfg.image_size))
    )
    tcfg = TrainConfig(
        batch_size=dp,
        learning_rate=1e-3,
        use_pallas=True,
        zero_stage=1,
        telemetry_level="scalars",
        collective_timing="sampled",
        collective_timing_interval=interval,
    )
    tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=dp))
    tr.step_fast(batch)  # compile + warm
    records = tr.collective_time_records(force=True)  # warm the sampler
    step_s = float("inf")
    sample_s = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(num_steps):
            m = tr.step_fast(batch)
        jax.block_until_ready(m["loss"])
        step_s = min(step_s, (time.perf_counter() - t0) / num_steps)
        t0 = time.perf_counter()
        records = tr.collective_time_records(force=True)
        sample_s = min(sample_s, time.perf_counter() - t0)
    # The deployed cadence: fit_loop ticks the sampler once per logging
    # boundary (log_every steps), and the sampler fires every interval-th
    # tick — one sample pass per log_every x interval steps.
    steps_between = log_every * interval
    overhead = sample_s / (steps_between * step_s)
    emit(
        {
            "metric": (
                f"collective_timing_overhead (sampled/{interval}, "
                f"manual zero1 dp{dp}"
                f"{', cpu-fallback mesh' if fallback else ''}, {chip})"
            ),
            "value": round(overhead * 100, 3),
            "unit": "percent",
            "step_time_s": round(step_s, 6),
            "sample_cost_s": round(sample_s, 6),
            "steps_between_samples": steps_between,
            "n_sites": len(records) - 1 if records else 0,
            "budget_pct": 2.0,
            "within_budget": bool(overhead < 0.02),
        }
    )
    # The measured per-site rows (and the α-β fit) — the hardware
    # window's re-fit evidence, schema-lintable like every bench line.
    for rec in records:
        print(json.dumps(rec), flush=True)


def bench_memory_table():
    """The per-preset live-bytes table (docs/OBSERVABILITY.md, HBM
    accounting): for every registered preset, the analytic live-bytes
    model of its train state — replicated (the single-chip anchor) AND
    per-replica at the preset's DECLARED mesh — emitted as one stamped
    bench row each, entirely from abstract shapes (jax.eval_shape: the
    pod preset's ~GBs of params are never materialized, so the table runs
    on any host). A final row carries the MEASURED device watermarks of
    the current backend (empty fields on CPU, which has no allocator
    stats) so analytic-vs-measured reconciliation has both sides in one
    log."""
    from glom_tpu.parallel.sharding import denoise_param_specs, opt_state_specs
    from glom_tpu.tracing.memory import hbm_watermarks
    from glom_tpu.utils.metrics import live_bytes_model
    from glom_tpu.utils.presets import PRESETS

    chip = detect_chip()
    for name in sorted(PRESETS):
        p = PRESETS[name]
        cfg, tcfg = p.model, p.train
        abstract = jax.eval_shape(
            lambda k, cfg=cfg, tcfg=tcfg: create_train_state(k, cfg, tcfg)[0],
            jax.random.PRNGKey(0),
        )
        replicated = live_bytes_model(
            abstract.params, abstract.opt_state, axis_sizes={},
            param_specs=None, opt_specs=None, grad_specs=None,
        )
        pspecs = denoise_param_specs("hidden")
        opt_specs = opt_state_specs(abstract.opt_state, pspecs)
        axis_sizes = dict(zip(p.mesh.axis_names, p.mesh.shape))
        sharded = live_bytes_model(
            abstract.params, abstract.opt_state, axis_sizes=axis_sizes,
            param_specs=pspecs, opt_specs=opt_specs, grad_specs=pspecs,
        )
        total = sum(replicated.values())
        emit(
            {
                "metric": f"live_bytes_model_total ({name}, replicated)",
                "value": total,
                "unit": "bytes",
                **replicated,
                **{f"mesh_{k}": v for k, v in sharded.items()},
                "mesh": dict(zip(p.mesh.axis_names, p.mesh.shape)),
                "zero_stage": tcfg.zero_stage,
            }
        )
    wm = hbm_watermarks()
    emit(
        {
            "metric": f"hbm_watermarks (measured, {chip})",
            "value": wm.get("hbm_bytes_in_use", -1),
            "unit": "bytes",
            **wm,
            "hbm_available": bool(wm),
        }
    )


def bench_span_overhead(span_iters: int = 20000, num_steps: int = 6,
                        repeats: int = 3):
    """The span-overhead bar (acceptance: < 1% per-step on the CPU bench
    path): measure the per-close cost of the fit loop's aggregated host
    span (tracing/spans.py) over `span_iters` closes, measure the
    cpu-fallback train step the fit loop would wrap, and emit the ratio.
    Direct per-call measurement rather than an A/B of two fit loops: the
    span cost is microseconds against a multi-ms step, far below loop-level
    run-to-run noise — an A/B would measure the noise, not the span."""
    import time

    from glom_tpu.tracing.spans import SpanAggregator, span

    chip = detect_chip()
    agg = SpanAggregator()
    t0 = time.perf_counter()
    for _ in range(span_iters):
        with span("host_step_dispatch", aggregator=agg):
            pass
    span_cost = (time.perf_counter() - t0) / span_iters

    # The same cpu-fallback config bench_train_step times.
    cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
    tcfg = TrainConfig(batch_size=4, learning_rate=3e-4)
    state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    step = jax.jit(
        make_train_step(cfg, tcfg, optimizer, with_grad_norm=False),
        donate_argnums=(0,),
    )
    img = jax.random.normal(
        jax.random.PRNGKey(1), (4, 3, cfg.image_size, cfg.image_size),
        jnp.float32,
    )
    rng = jax.random.PRNGKey(2)
    state, m = step(state, img, rng)  # compile
    jax.block_until_ready(m["loss"])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for i in range(num_steps):
            state, m = step(state, img, jax.random.fold_in(rng, i))
        jax.block_until_ready(m["loss"])
        best = min(best, (time.perf_counter() - t0) / num_steps)

    # fit_loop opens two aggregated spans per sustained step
    # (host_data_next + host_step_dispatch).
    overhead = 2 * span_cost / best
    emit(
        {
            "metric": f"span_overhead (2 host spans vs cpu bench step, {chip})",
            "value": round(overhead * 100, 4),
            "unit": "percent",
            "span_cost_us": round(span_cost * 1e6, 3),
            "step_time_s": round(best, 6),
            "budget_pct": 1.0,
            "within_budget": bool(overhead < 0.01),
        }
    )


def run_loss_curve(num_steps: int, out_path: str, trace_capture=None):
    from glom_tpu.data import shapes_dataset
    from glom_tpu.train.trainer import Trainer
    from glom_tpu.utils.metrics import MetricsWriter
    from glom_tpu.utils.presets import get_preset

    chip = detect_chip()
    on_tpu = chip != "cpu"
    p = get_preset("cifar10")
    tcfg = TrainConfig(
        batch_size=p.train.batch_size,
        learning_rate=p.train.learning_rate,
        noise_std=p.train.noise_std,
        compute_dtype=p.train.compute_dtype if on_tpu else "float32",
        use_pallas=on_tpu,
    )
    writer = MetricsWriter(out_path, echo=True)
    trainer = Trainer(p.model, tcfg, metrics_writer=writer)
    data = shapes_dataset(tcfg.batch_size, p.model.image_size, seed=1)
    try:
        history = trainer.fit(
            data, num_steps, log_every=10, trace_capture=trace_capture
        )
    finally:
        if trace_capture is not None:
            trace_capture.close()

    k_iters = _train_iters(p.model, tcfg)
    steps_per_sec = history[-1]["steps_per_sec"]
    cips = steps_per_sec * tcfg.batch_size * k_iters
    writer.write(
        {
            "summary": True,
            "config": "cifar10",
            # Honest data provenance: the CIFAR-10 *config* trained on the
            # procedural shapes dataset — no real dataset ships in this
            # zero-egress environment (real data runs use --data-dir via
            # the CLI; see data/loaders.py).
            "data": "synthetic-shapes",
            "chip": chip,
            "steps": num_steps,
            "final_loss": history[-1]["loss"],
            "column_iters_per_sec_per_chip": round(cips, 2),
            "mfu": round(mfu(p.model, cips, chip=chip, backward=True), 4),
        }
    )
    writer.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--loss-curve", type=int, default=0, metavar="STEPS")
    ap.add_argument(
        "--out", default="results/cifar10_loss_curve.jsonl", help="loss-curve output"
    )
    ap.add_argument(
        "--preset", default=None,
        help="measure a preset's MODEL shape single-chip (e.g. imagenet224-pod)",
    )
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument(
        "--mult", type=int, default=None,
        help="FFW expansion override (--mult 2 = the pod's per-TP-rank f)",
    )
    ap.add_argument(
        "--telemetry-ab", action="store_true",
        help="A/B the in-graph telemetry overhead (scalars vs off) and "
        "emit the measured per-step percentage (< 2%% is the bar)",
    )
    ap.add_argument(
        "--collective-timing-ab", action="store_true",
        help="A/B the sampled per-collective wall-time harness on the "
        "manual zero1 path (off vs sampled; < 2%% is the bar) and emit "
        "the measured collective_time rows + the α-β time-model fit "
        "(docs/OBSERVABILITY.md, Capacity observatory)",
    )
    ap.add_argument(
        "--span-ab", action="store_true",
        help="measure the host-span overhead of the fit loop against the "
        "cpu bench step (< 1%% is the bar; docs/OBSERVABILITY.md)",
    )
    ap.add_argument(
        "--memory-table", action="store_true",
        help="emit the per-preset analytic live-bytes table (replicated + "
        "declared-mesh per-replica) plus the measured HBM watermarks",
    )
    ap.add_argument(
        "--trace-steps", default=None, metavar="A:B",
        help="with --loss-curve: capture an XLA trace of training steps "
        "A..B into --trace-dir (window metadata stamped into the stream)",
    )
    ap.add_argument(
        "--trace-dir", default="/tmp/glom_tpu_trace", metavar="DIR",
        help="where --trace-steps writes the XProf trace",
    )
    args = ap.parse_args()
    # Backend gate (docs/OBSERVABILITY.md): probe through the watchdog
    # before ANY in-process backend touch, register it so every emitted
    # row carries backend_state, and never record a dead zero — an
    # unmeasurable host gets one "error"-kind record (value null).
    from glom_tpu.telemetry.sinks import bench_bootstrap

    if not bench_bootstrap("train_step column_iters_per_sec_per_chip"):
        raise SystemExit(0)
    if args.trace_steps and not args.loss_curve:
        raise SystemExit("--trace-steps requires --loss-curve (the stepped "
                         "path; chain benches capture whole measurements)")
    if args.telemetry_ab:
        bench_telemetry_overhead()
    elif args.collective_timing_ab:
        bench_collective_timing_overhead()
    elif args.span_ab:
        bench_span_overhead()
    elif args.memory_table:
        bench_memory_table()
    elif args.loss_curve > 0:
        cap = None
        if args.trace_steps:
            from glom_tpu.tracing.capture import TraceCapture

            cap = TraceCapture.parse(args.trace_steps, args.trace_dir)
        run_loss_curve(args.loss_curve, args.out, trace_capture=cap)
    elif args.preset:
        bench_preset_train_step(args.preset, args.batch, args.mult)
    else:
        bench_train_step(args.batch)
