#!/usr/bin/env bash
# Round-5 hardware work queue: everything that needs the real TPU chip,
# in priority order, each step logged and failure-isolated. The axon
# tunnel was down for all of round 5's session — run this whole file the
# moment `python -c "import jax; jax.devices()"` initializes again.
#
# Usage: bash run_hw_queue.sh        (from /root/repo; ~30-60 min total)
set -u
cd "$(dirname "$0")"
mkdir -p results/hw_queue
log() { echo "=== [$(date +%H:%M:%S)] $*"; }

step() {  # step <name> <timeout_s> <cmd...>; returns the command's rc
    local name=$1 to=$2; shift 2
    log "START $name"
    timeout "$to" "$@" 2>&1 | tee "results/hw_queue/${name}.log"
    local rc=${PIPESTATUS[0]}
    log "DONE $name (rc=$rc)"
    return "$rc"
}

# 0. Pre-flight: glom-lint (glom_tpu/analysis) over the tree against the
#    reviewed baseline. Pure-CPU whole-program AST pass, seconds — a
#    hardware window must never start on code with a known
#    collective/schema/lockset violation (exactly the class of silent
#    mismatch that burns a pod session before anyone notices the
#    evidence trail is wrong). The fingerprint cache makes repeat queue
#    runs near-instant; staleness is content-hashed per dependency
#    closure, so a stale reuse is impossible, not just unlikely.
step lint 300 python -m glom_tpu.analysis glom_tpu/ --baseline analysis_baseline.json \
    --cache results/hw_queue/lint_cache.json || {
    log "glom-lint found NEW violations — fix (or review into the baseline) before burning a hardware window"; exit 1; }

# 0b. Gate: is the backend actually up? (bounded — never hangs)
step probe 120 python -c "import jax; print(jax.devices())" || true
grep -q "TpuDevice\|tpu" results/hw_queue/probe.log || {
    log "backend still down; aborting queue"; exit 1; }

# 1. Hardware parity first (16 checks incl. the new fused-loop
#    primal-vs-VJP, remat-grad, and combined-grid checks) — the
#    measurement steps below are meaningless if these fail, so a parity
#    failure STOPS the queue here.
step tpu_validate 2400 python -u tpu_validate.py || {
    log "hardware parity FAILED — not measuring on broken kernels"; exit 1; }

# 2. The driver metric of record: fwd + train-step lines.
step bench 2400 python -u bench.py

# 3. Pod per-TP-rank anchor — round 4 measured 673 on the scan-path
#    backward; the whole-loop VJP (remat mode, unchained dw) now covers
#    this shape. Median of 3.
for i in 1 2 3; do
    step "pod_anchor_$i" 1800 python -u bench_train.py --preset imagenet224-pod --batch 16 --mult 2
done

# 4. Batch-128 point on the AUTO-ROUTED path (grad_accum=2 over
#    batch-64 fused-loop microbatches; round-4 scan-path row was 3489 =
#    0.96x vs baseline).
for i in 1 2 3; do
    step "batch128_$i" 1800 python -u bench_train.py --batch 128
done

# 5. SP crossover rows at the shapes the selector governs (pod
#    d=1024/L=12, L=6 class, batched B=8) — appends to
#    results/sp_crossover.jsonl; re-run the table-driven selector test
#    afterwards.
step sp_crossover 2400 python -u bench_sp_crossover.py

# 6. FFW-backward scheduling sweep (the last ~7%: tile ladder at the
#    chained-accumulator working set).
step ffw_bwd_sched 2400 python -u scratch/ffw_bwd_sched_probe.py

# 7. ZeRO weight-update A/Bs (this round's distributed-optimizer PR):
#    zero_stage 0 vs 1 vs 2-with-accum, and quantized vs f32 reduce, at
#    dp = all visible devices. With today's single-chip tunnel the script
#    self-downgrades to the labelled virtual-CPU mesh (ratio + analytics
#    only) — the rows price for real the first window a SLICE answers;
#    at dp>=8, expect zero1 ~= zero0 step time (same total wire bytes,
#    (dp-1)/dp*(G+P) vs 2(dp-1)/dp*G) with opt-state HBM down ~dp x.
for i in 1 2 3; do
    step "zero_ab_$i" 1800 python -u bench_zero.py
done

# 8. Pod-shape ZeRO anchor: the per-TP-rank single-chip anchor (step 3)
#    re-run with sharded-update analytics stamped on the record — pairs
#    with the dp=64 pod projection in docs/PARALLELISM.md (ZeRO section).
step pod_zero_record 1800 python -u bench_train.py --preset imagenet224-pod --batch 16 --mult 2

# 9. Telemetry overhead A/B on the real chip (the < 2% per-step bar for
#    telemetry_level=scalars; docs/OBSERVABILITY.md) — if this exceeds
#    budget on hardware, the scalars bundle needs a diet before the
#    always-on rollout.
step telemetry_ab 1800 python -u bench_train.py --telemetry-ab

# 9b. Span-overhead bar (< 1% per-step for the fit loop's host spans) and
#     the per-preset memory table with MEASURED HBM watermarks — the
#     analytic live-bytes model finally reconciled against a real
#     allocator (docs/OBSERVABILITY.md, HBM accounting).
step span_ab 900 python -u bench_train.py --span-ab
step memory_table 900 python -u bench_train.py --memory-table

# 9c. One step-windowed XLA trace of the flagship loss-curve path (steps
#     20:24, past compile) for the XProf phase breakdown — trace dir is
#     stamped into the log's note records.
step trace_capture 1800 python -u bench_train.py --loss-curve 30 \
    --out results/hw_queue/trace_curve.jsonl \
    --trace-steps 20:24 --trace-dir results/hw_queue/xla_trace

# 9d-. Chaos gate BEFORE the serve sweep (docs/RESILIENCE.md): SIGKILL a
#      real training worker mid-run and require the resumed worker to
#      finish with a continuous, schema-clean evidence trail. A serving
#      stack about to be load-swept on real hardware must first prove it
#      survives a kill — recovery bugs found during the sweep burn the
#      window.
step chaos 1200 python -m glom_tpu.resilience --scenario kill-train \
    --dir results/hw_queue/chaos --steps 6 || {
    log "chaos kill-and-resume FAILED — not sweeping a serving stack that cannot recover"; exit 1; }

# 9d--. Pod-preemption gate (docs/RESILIENCE.md, coordinated preemption):
#       SIGTERM a strict subset of a 2-process pod, then all of it — the
#       two-phase save barrier must commit ONE common step on every host
#       inside the grace deadline and the relaunched gang must resume
#       from it. A pod about to burn a real multi-host window must first
#       prove its grace save cannot leave hosts committed at different
#       steps (the silent-inconsistent-resume failure class).
step chaos_pod 1200 python -m glom_tpu.resilience --scenario preempt-pod \
    --dir results/hw_queue/chaos_pod --steps 8 --hosts 2 || {
    log "pod-preemption barrier FAILED — an uncoordinated pod checkpoint would corrupt the window's resume"; exit 1; }

# 9d. Serving SLO sweep (glom_tpu/serve, docs/SERVING.md): AOT warmup per
#     bucket, closed-loop throughput ceiling, offered-load p50/p95/p99
#     latency rows, and the consensus early-exit iteration histogram on
#     the flagship bf16 fused route. Gated against its own baseline in
#     step 11b.
step bench_serve 2400 python -u bench_serve.py

# 9e. Pod-scale serving (this round's tentpole, docs/SERVING.md): the
#     two-tier exit A/B over heterogeneous traffic with 2-engine fan-out
#     (the serve_mean_executed_iters pair is the measured per-request
#     early-exit win), then the SHARDED engine route — every bucket
#     through the (data=4) serve mesh with the while-loop witness
#     collectives counted on the bucket_stats records. First live window:
#     read the sharded ceiling vs 9d's single-chip ceiling (the
#     serve-mesh wire cost is provisioned at the budget, so the delta is
#     the real witness-psum price), then baseline both via step 11b.
step bench_serve_two_tier 2400 python -u bench_serve.py --engines 2 --two-tier-ab --hetero 0.5
step bench_serve_sharded 2400 python -u bench_serve.py --mesh-data 4

# 9f. Streaming warm-start A/B (this round's tentpole, docs/SERVING.md
#     "Streaming"): frame-sequence traffic per stream through the
#     session column cache vs cold-start — the
#     serve_temporal_mean_iters pair plus serve_temporal_iters_saved is
#     the measured per-request win on real hardware (bf16 flagship
#     route: the warm levels0 staging and donation actually resolve
#     here, unlike the CPU smoke). Baselined via step 11b.
step bench_serve_temporal 2400 python -u bench_serve.py --temporal --streams 8 --frames 6

# 9h. Ragged paged sweep + paged warm-path A/B (this round's tentpole,
#     docs/SERVING.md "Paged column memory"/"Ragged admission"): the
#     same mixed-resolution streamed traffic served padded through the
#     bucket ladder vs packed through the ragged page ladder. On real
#     hardware this measures what the CPU smoke cannot: the actual
#     PCIe-vs-HBM warm-path dispatch latency delta (the paged arm's
#     levels0_h2d_bytes is 0 — its warm state never leaves HBM) and the
#     MXU time the pad tokens stop burning. The serve_pad_waste pair,
#     both arms' warm/cold dispatch-latency rows, and the per-arm
#     levels0_h2d_bytes feed the step 11b serve compare baseline (pad
#     and h2d rows gate as COSTS — telemetry/compare.py).
step bench_serve_ragged 2400 python -u bench_serve.py --ragged --streams 8 --frames 6
step ragged_gate 120 python - results/hw_queue/bench_serve_ragged.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
waste, h2d = {}, {}
for r in rows:
    m = r.get("metric", "")
    if m.startswith("serve_pad_waste ("):
        waste[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_levels0_h2d_bytes ("):
        h2d[m.split("(")[1].split(",")[0]] = (r["value"], r.get("n_page_warm", 0))
assert set(waste) == {"bucket-ladder", "ragged-paged"}, f"arms missing: {waste}"
assert waste["ragged-paged"] < waste["bucket-ladder"], f"pad waste not reduced: {waste}"
b, w = h2d.get("ragged-paged", (None, 0))
assert b == 0 and w > 0, f"paged warm path not zero-transfer: {h2d}"
print(f"OK: pad waste {waste['bucket-ladder']}% -> {waste['ragged-paged']}%; "
      f"0 warm levels0 bytes over {w} page-warm rows")
EOF

# 9i. Delta streaming A/B gate (ISSUE 12, docs/SERVING.md "Delta
#     streaming"): whole-state paged warm vs delta-chain storage + the
#     sparse incremental route over O(1)-shaped frame traffic (shared
#     scene bases, bitwise holds, a one-patch moving region). On real
#     hardware this prices what the CPU smoke cannot: the residual
#     probe + sparse scatter on the device write-back path, and the HBM
#     actually freed per live stream. The gate requires the delta arm
#     STRICTLY below whole-state on BOTH mean executed iters/frame
#     (and < 2) and bytes_per_stream (>= 3x), with the threshold-0
#     reconstruction parity probe BITWISE — rows feed the step 11b
#     serve baseline (bytes/chain rows gate as costs).
step bench_serve_delta 2400 python -u bench_serve.py --temporal --delta --streams 8 --frames 16
step delta_gate 120 python - results/hw_queue/bench_serve_delta.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
iters, bps, parity = {}, {}, None
for r in rows:
    m = r.get("metric", "")
    if m.startswith("serve_delta_mean_iters ("):
        iters[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_delta_bytes_per_stream ("):
        bps[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_delta_parity ("):
        parity = r["value"]
assert set(iters) == {"whole-state", "delta"}, f"arms missing: {iters}"
assert iters["delta"] < 2.0 and iters["delta"] < iters["whole-state"], (
    f"incremental path did not beat the bar: {iters}")
assert bps["delta"] * 3 <= bps["whole-state"], f"bytes not >=3x down: {bps}"
assert parity == 1.0, "threshold-0 delta reconstruction is NOT bitwise"
print(f"OK: iters {iters['whole-state']} -> {iters['delta']}, bytes/stream "
      f"{bps['whole-state']} -> {bps['delta']}, parity bitwise")
EOF

# 9l. Block-banded consensus + pool-aliasing A/B gate (ISSUE 16,
#     docs/SERVING.md "Block-banded ragged consensus" / "Pool
#     aliasing"): the same ragged streamed traffic under the windowed
#     gather vs the banded route vs banded + in-place aliasing. On real
#     hardware this prices what the CPU smoke cannot: the HBM the
#     W-fold k/v gather actually duplicates per dispatch (the banded
#     working set is page_tokens-fold smaller — the admission ceiling
#     moves), and the pool bytes the donated in-place write-back stops
#     copying. The gate requires banded peak_window_bytes STRICTLY
#     below windowed, the largest admissible ragged signature STRICTLY
#     larger, aliased pool bytes moved STRICTLY below CoW with the
#     warm path still zero-transfer, and the threshold-0 parity row
#     BITWISE — rows feed the step 11b serve baseline (peak-window and
#     pool-bytes rows gate as costs).
step bench_serve_banded 2400 python -u bench_serve.py --banded-ab --streams 8 --frames 6
step banded_gate 120 python - results/hw_queue/bench_serve_banded.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
peak, sig, moved, h2d, parity = {}, {}, {}, {}, None
for r in rows:
    m = r.get("metric", "")
    if m.startswith("serve_ragged_peak_window_bytes ("):
        peak[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_ragged_max_signature_pages ("):
        sig[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_pool_bytes_moved ("):
        moved[m.split("(")[1].split(",")[0]] = r["value"]
    if m.startswith("serve_levels0_h2d_bytes ("):
        h2d[m.split("(")[1].split(",")[0]] = (r["value"], r.get("n_page_warm", 0))
    if m.startswith("serve_banded_parity ("):
        parity = r["value"]
assert set(peak) == {"windowed", "banded", "banded-alias"}, f"arms missing: {peak}"
assert peak["banded"] < peak["windowed"], f"banded working set not smaller: {peak}"
assert sig["banded"] > sig["windowed"], f"max signature did not grow: {sig}"
assert moved["banded-alias"] < moved["banded"], f"aliasing moved no fewer bytes: {moved}"
b, w = h2d.get("banded-alias", (None, 0))
assert b == 0 and w > 0, f"aliased warm path not zero-transfer: {h2d}"
assert parity == 1.0, "threshold-0 banded vs windowed dispatch is NOT bitwise"
print(f"OK: peak window {peak['windowed']} -> {peak['banded']} bytes; max "
      f"signature {sig['windowed']} -> {sig['banded']} pages; pool bytes "
      f"{moved['banded']} -> {moved['banded-alias']}; parity bitwise")
EOF

# 9g. Request-tracing overhead gate + pod aggregation (this round's
#     tentpole, docs/OBSERVABILITY.md): full trace stamping (ids minted
#     per submit, per-dispatch scope, per-request resolve leaves) must
#     cost < 2% end-to-end latency on real hardware — the A/B emits
#     serve_trace_overhead in percent and the gate reads it back. Then
#     the preempt-pod gate's per-host streams (step 9d--) must merge
#     into ONE consistent pod timeline: clock families reconciled via
#     the anchor records, barrier chains complete, --strict gating.
step bench_serve_trace_ab 2400 python -u bench_serve.py --trace-ab
step trace_overhead_gate 120 python - results/hw_queue/bench_serve_trace_ab.log <<'EOF'
import sys
from glom_tpu.telemetry import schema  # noise-tolerant line reader
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
ov = [r for r in rows if r.get("metric", "").startswith("serve_trace_overhead")]
assert ov, "no serve_trace_overhead row in the trace A/B log"
v = ov[-1]["value"]
assert isinstance(v, (int, float)), f"trace overhead UNMEASURED: {ov[-1]}"
assert v <= 2.0, f"trace overhead {v}% exceeds the 2% stamping budget"
print(f"OK: trace stamping overhead {v}% within the 2% budget")
EOF
step pod_aggregate 300 python -m glom_tpu.telemetry aggregate \
    results/hw_queue/chaos_pod/metrics_h0.jsonl \
    results/hw_queue/chaos_pod/metrics_h1.jsonl --strict --timeline 20

# 9j. Capacity observatory (ISSUE 13, docs/OBSERVABILITY.md): the first
#     real TPU window measures per-collective wall-time on the manual
#     zero1 path (the standing hardware-window debt item) and RE-FITS
#     the α-β comm_time_model from the measured points — the
#     collective_time rows land in the bench log, so the next window's
#     drift is priced against THIS window's fit via the compare gate.
#     Both overhead gates hold the <2% bar on real hardware: the sampled
#     timing harness amortized at the deployed cadence, and the dispatch
#     phase split (queue_wait/pack/h2d/device/resolve) on the serve path
#     — on a real chip the h2d/device split finally prices the PCIe-vs-
#     HBM boundary the CPU smoke cannot see.
step collective_timing_ab 1800 python -u bench_train.py --collective-timing-ab
step collective_timing_gate 120 python - results/hw_queue/collective_timing_ab.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
ov = [r for r in rows if r.get("metric", "").startswith("collective_timing_overhead")]
assert ov, "no collective_timing_overhead row in the A/B log"
v = ov[-1]["value"]
assert isinstance(v, (int, float)), f"timing overhead UNMEASURED: {ov[-1]}"
assert v <= 2.0, f"sampled collective-timing overhead {v}% exceeds the 2% bar"
sites = [r for r in rows if r.get("kind") == "collective_time"
         and r.get("site") not in (None, "comm_time_model")]
model = [r for r in rows if r.get("site") == "comm_time_model"]
assert sites and model, "no measured collective_time rows / model fit in the log"
assert all(r["wall_ms"] > 0 for r in sites), "zero wall_ms on a measured site"
print(f"OK: timing overhead {v}% within 2%; {len(sites)} sites measured, "
      f"alpha={model[-1]['alpha_ms']}ms beta={model[-1]['beta_ms_per_byte']}ms/B")
EOF
step phase_ab 2400 python -u bench_serve.py --phase-ab
step phase_overhead_gate 120 python - results/hw_queue/phase_ab.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
ov = [r for r in rows if r.get("metric", "").startswith("serve_phase_overhead")]
assert ov, "no serve_phase_overhead row in the phase A/B log"
v = ov[-1]["value"]
assert isinstance(v, (int, float)), f"phase overhead UNMEASURED: {ov[-1]}"
assert v <= 2.0, f"phase-split overhead {v}% exceeds the 2% stamping budget"
print(f"OK: phase-split overhead {v}% within the 2% budget")
EOF

# 9k. Elastic serving ramp gate (ISSUE 15, docs/SERVING.md "Elastic
#     serving"): the offered-load ramp through the REAL autoscaler on
#     real hardware — the spike must scale the fleet OUT (spawn + full
#     AOT warmup off the hot path, admission strictly after precompile),
#     the calm must scale it back IN (graceful drain: migrate sessions,
#     release devices), and every ticket must be conserved. On TPU the
#     spawn_ms row finally prices a real device-group warmup (the number
#     a production autoscaler's dwell must exceed), and the row joins
#     the 11b serve baseline so spawn-latency regressions gate.
step ramp_serve 2400 python -u bench_serve.py --ramp
step ramp_serve_gate 120 python - results/hw_queue/ramp_serve.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
peak = [r for r in rows if r.get("metric", "").startswith("serve_ramp_n_engines_peak")]
cons = [r for r in rows if r.get("metric", "").startswith("serve_ramp_tickets_conserved")]
assert peak and cons, "ramp rows missing from the elastic bench log"
assert peak[-1]["value"] >= 2, f"fleet never scaled out: {peak[-1]}"
assert peak[-1]["n_scale_ins"] >= 1, f"fleet never scaled back in: {peak[-1]}"
assert cons[-1]["value"] == 1.0, f"ramp tickets NOT conserved: {cons[-1]}"
tl = peak[-1]["timeline"]
print(f"OK: fleet timeline {tl}, tickets conserved")
EOF

# 9m. Workload-observatory gate (ISSUE 17, docs/SERVING.md "Record and
#     replay"): a seeded diurnal scenario replayed through the REAL
#     autoscaler on real hardware. The gate requires exact ticket
#     conservation + the same per-request signature sequence as the
#     artifact, AND live forecast evidence: forecast records on every
#     closed window, each carrying the forecast_abs_err key, with at
#     least one matured (finite) predicted-vs-realized error — a
#     forecast that never scores is the silent-absence failure this
#     observatory exists to kill. Rows join the 11b serve baseline so
#     pacing/forecast regressions gate.
step workload_serve 2400 python -u bench_serve.py --scenario diurnal \
    --scenario-duration 6
step workload_gate 120 python - results/hw_queue/workload_serve.log <<'EOF'
import sys
from glom_tpu.telemetry import schema
rows = [r for _, r in schema.iter_json_lines(open(sys.argv[1]))]
cons = [r for r in rows
        if r.get("metric", "").startswith("serve_workload_tickets_conserved")]
assert cons, "workload rows missing from the bench log"
assert cons[-1]["value"] == 1.0, f"replay tickets NOT conserved: {cons[-1]}"
ws = [r for r in rows if r.get("event") == "workload_summary"][-1]
assert ws["signature_sequence_match"] is True, ws
fc = [r for r in rows if r.get("kind") == "forecast"]
assert fc, "no forecast records emitted over the scenario"
missing = [r for r in fc if "forecast_abs_err" not in r]
assert not missing, f"forecast records without the error key: {missing[:2]}"
scored = [r for r in fc
          if isinstance(r.get("forecast_abs_err"), (int, float))]
assert scored, "no forecast window ever matured (error never scored)"
lag = [r for r in rows
       if r.get("metric", "").startswith("serve_workload_pacing_lag")]
print(f"OK: {len(fc)} forecast records ({len(scored)} scored, last "
      f"abs_err {scored[-1]['forecast_abs_err']}), pacing lag "
      f"{lag[-1]['value'] if lag else '?'}ms, tickets conserved")
EOF

# 9n. Decision-observatory gate (PR 18, docs/SERVING.md "Anticipatory
#     autoscaling" + docs/OBSERVABILITY.md schema v10): the flash-crowd
#     anticipatory-vs-reactive A/B on real hardware — a crowd past one
#     engine's service rate drives the SAME replayed records through the
#     PR 14 reactive baseline and the forecast + warm-pool fleet. The
#     bench ASSERTS the anticipatory arm failed no more tickets AND
#     landed a strictly lower p99; both arms' decision chains must then
#     reconstruct from the JSONL alone under `telemetry audit --strict`
#     (evidence conservation bit-for-bit, chain integrity, regret
#     scored). On TPU the spare's spawn_ms prices a REAL precompiled
#     device-group promote vs a cold spawn. Rows join the 11b serve
#     baseline so regret/late-decision/lead-violation growth gates.
step elastic_ab 2400 python -u bench_serve.py --scenario flash-crowd \
    --scenario-duration 12 --scenario-crowd-rps 400 --elastic-ab \
    --elastic-ab-out results/hw_queue/elastic_ab
step elastic_audit 120 python -m glom_tpu.telemetry audit --strict \
    results/hw_queue/elastic_ab_reactive.jsonl \
    results/hw_queue/elastic_ab_anticipatory.jsonl

# 9o. Multi-tenant QoS gate (ISSUE 19, docs/SERVING.md "SLO classes" +
#     docs/OBSERVABILITY.md schema v11): the same flash crowd, dealt a
#     seeded premium/standard/batch mix, drives a classless shared-FIFO
#     fleet and the deficit-weighted-fair QoS fleet whose lanes
#     PARTITION the same queue depth. The bench ASSERTS premium p99
#     strictly below the classless baseline, batch held at or above the
#     starvation floor, EXACT per-class ticket conservation on both
#     arms, and both decision chains passing `telemetry audit --strict`
#     (weighted regret scored from the stamped class_weights). Rows
#     join the 11b serve baseline so per-class p99 / served-fraction /
#     shed growth gates.
step qos_ab 2400 python -u bench_serve.py --scenario flash-crowd \
    --scenario-duration 12 --scenario-crowd-rps 400 \
    --class-mix 'premium=0.2,standard=0.3,batch=0.5' --qos-ab \
    --qos-ab-out results/hw_queue/qos_ab
step qos_audit 120 python -m glom_tpu.telemetry audit --strict \
    results/hw_queue/qos_ab_classless.jsonl \
    results/hw_queue/qos_ab_qos.jsonl

# 10. Schema lint: every JSON row this queue produced must validate
#     against the versioned event schema (glom_tpu/telemetry/schema.py).
#     Shell noise in the logs is skipped; --allow-unstamped because the
#     scratch harnesses still emit legacy unstamped rows — the
#     bench*.py rows (incl. longctx/sp_crossover since PR 3) are all
#     stamped and validate strictly (CI enforces that on every push).
step schema_lint 300 python -m glom_tpu.telemetry --allow-unstamped results/hw_queue/*.log

# 11. Bench-trajectory regression gate: this queue's metric-of-record rows
#     vs the last committed good trajectory. UNMEASURED rows are MISSING,
#     never zero (the round-5 pollution this gate exists to end); a
#     beyond-noise regression fails the queue loudly. On pass, the fresh
#     rows become the next baseline.
if [ -f results/bench_baseline.jsonl ]; then
    step bench_compare 300 python -m glom_tpu.telemetry compare \
        results/bench_baseline.jsonl results/hw_queue/bench.log || {
        log "bench trajectory REGRESSION (results/hw_queue/bench_compare.log)"
        exit 1
    }
fi
grep -ah '^{' results/hw_queue/bench.log > results/bench_baseline.jsonl 2>/dev/null || true

# 11b. Serving-trajectory gate: the SLO rows (latency percentiles regress
#      UP, throughput/ceiling regress DOWN, auto-iters regress UP — unit-
#      derived) against the last good serve baseline; refresh on pass.
grep -ah '^{' results/hw_queue/bench_serve.log \
    results/hw_queue/bench_serve_two_tier.log \
    results/hw_queue/bench_serve_sharded.log \
    results/hw_queue/bench_serve_temporal.log \
    results/hw_queue/bench_serve_ragged.log \
    results/hw_queue/bench_serve_delta.log \
    results/hw_queue/bench_serve_banded.log \
    results/hw_queue/collective_timing_ab.log \
    results/hw_queue/phase_ab.log \
    results/hw_queue/ramp_serve.log \
    results/hw_queue/workload_serve.log \
    results/hw_queue/elastic_ab.log \
    results/hw_queue/qos_ab.log \
    > results/hw_queue/serve_candidate.jsonl 2>/dev/null || true
if [ -f results/serve_baseline.jsonl ]; then
    step serve_compare 300 python -m glom_tpu.telemetry compare \
        results/serve_baseline.jsonl results/hw_queue/serve_candidate.jsonl || {
        log "serve trajectory REGRESSION (results/hw_queue/serve_compare.log)"
        exit 1
    }
fi
cp results/hw_queue/serve_candidate.jsonl results/serve_baseline.jsonl 2>/dev/null || true

log "queue complete — paste numbers into results/profiles/PROFILE.md, "
log "docs/PARALLELISM.md (pod anchor + ZeRO table), results/batch_curve.jsonl,"
log "and re-run: python -m pytest tests/test_parallel.py tests/test_zero.py -q"
